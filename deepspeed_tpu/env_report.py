"""Environment / op-compatibility report.

Reference: ``deepspeed/env_report.py`` (the ``ds_report`` CLI): prints the
op-builder compatibility matrix + torch/cuda versions. TPU version reports
the jax stack, device inventory, mesh capability, and the op registry
(pallas kernels, native AIO) status.
"""

import importlib
import os
import sys


GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def _version(mod_name):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except ImportError:
        return None


def op_report():
    """Op registry status lines (reference op_report: compatible/installed)."""
    from .ops.registry import registry
    # probe ops so their registration side effects run
    from .ops import aio as _aio  # noqa: F401
    _aio.aio_available()
    from .ops import cpu_optim as _cpu_optim  # noqa: F401
    _cpu_optim.cpu_optim_available()
    for mod in ("attention", "attention_folded", "normalization", "quantizer",
                "fused_optimizer", "rope",
                "evoformer_attn", "spatial", "cpu_optim", "paged_attention",
                "grouped_matmul", "sampling",
                "sparse_attention.sparse_self_attention"):
        try:
            importlib.import_module(f".ops.{mod}", package=__package__)
        except ImportError:
            pass
    lines = ["-" * 64, "op name " + "." * 40 + " backend  status", "-" * 64]
    for name, info in sorted(registry.report().items()):
        status = OKAY if info.compatible else NO
        lines.append(f"{name} {'.' * max(1, 48 - len(name))} "
                     f"[{info.backend}] {status}")
    return "\n".join(lines)


def debug_report():
    import jax
    lines = []
    lines.append("-" * 64)
    lines.append("DeepSpeed-TPU general environment info:")
    lines.append("-" * 64)
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        v = _version(mod)
        lines.append(f"{mod} version {'.' * max(1, 40 - len(mod))} "
                     f"{v if v else NO}")
    lines.append(f"python version {'.' * 34} {sys.version.split()[0]}")
    try:
        # RESOLVED variant (env override OR silicon-A/B sentinel promotion),
        # not the raw env var: a FOLDED_PROVEN run with the env unset still
        # executes the folded kernels and must report as such
        from .ops.attention import resolved_attention_variant
        lines.append(f"flash-attention variant {'.' * 25} "
                     f"{resolved_attention_variant()}")
    except Exception as e:  # pragma: no cover
        lines.append(f"flash-attention variant {'.' * 25} {NO} ({e})")
    try:
        # per-leg kernel dispatch: where the table comes from (measured
        # autotune cache vs built-in heuristics) and what the bench shape
        # resolves to right now — so every saved report pins the kernels
        from .ops import kernel_dispatch
        lines.append(f"attn dispatch table {'.' * 29} "
                     f"{kernel_dispatch.table_source()}")
        lines.append(f"attn dispatch @ bench shape {'.' * 21} "
                     f"{kernel_dispatch.resolved_note()}")
    except Exception as e:  # pragma: no cover
        lines.append(f"attn dispatch table {'.' * 29} {NO} ({e})")
    try:
        # speculative decoding: where drafts come from under the current
        # config — the fused program's on-device ring buffer, or the host
        # prompt-lookup fallback (gate off / per-token oracle path)
        from .inference.v2.config_v2 import SamplingConfig
        scfg = SamplingConfig()
        src = ("device ring-buffer (fused)" if scfg.fused_speculative_decode
               else "host prompt-lookup (per-token fallback)")
        lines.append(f"speculative draft source {'.' * 24} {src}")
    except Exception as e:  # pragma: no cover
        lines.append(f"speculative draft source {'.' * 24} {NO} ({e})")
    try:
        # continuous fused serving: whether the scheduler overlaps prefill
        # + admission with the in-flight fused K-step decode wave, or
        # falls back to the legacy exclusive modes (per-token decode
        # whenever any prefill/arrival work exists)
        from .inference.v2.config_v2 import ContinuousFusionConfig
        ccfg = ContinuousFusionConfig()
        mode = ("overlapped (prefill rides the in-flight wave)"
                if ccfg.enabled else "exclusive (legacy gate)")
        lines.append(f"continuous fused serving {'.' * 24} {mode}")
    except Exception as e:  # pragma: no cover
        lines.append(f"continuous fused serving {'.' * 24} {NO} ({e})")
    try:
        # quantized TP serving: the resolved collective wire dtype (with its
        # precedence source — explicit config > DS_TPU_TP_WIRE env >
        # default) and whether WoQ×TP sharded kernels are available
        from .parallel.tp import resolve_tp_wire
        wire, source = resolve_tp_wire()
        base = wire["attn_out"]
        note = "" if wire["lm_head"] == base else " (lm_head fp)"
        lines.append(f"tp collective wire dtype {'.' * 24} "
                     f"{base}{note} [source: {source}]")
        from .inference.v2.model import check_woq_tp_support  # noqa: F401
        lines.append(f"woq x tp sharded kernels {'.' * 24} "
                     f"available (int8/int4/fp6 shard-major)")
    except Exception as e:  # pragma: no cover
        lines.append(f"tp collective wire dtype {'.' * 24} {NO} ({e})")
    try:
        # radix prefix cache: whether the default engine config would run
        # with cross-request KV reuse (COW forking), and why not when
        # disabled — the sliding-window gate lives in the engine, so here
        # we report the config default + the model-dependent caveat
        from .inference.v2.config_v2 import RaggedInferenceEngineConfig
        ecfg = RaggedInferenceEngineConfig()
        if ecfg.enable_prefix_caching:
            state = ("enabled (radix + COW fork; disabled at runtime "
                     "for sliding-window models)")
        else:
            state = "disabled (state_manager.enable_prefix_caching)"
        lines.append(f"prefix cache {'.' * 36} {state}")
        nt = len(ecfg.tenants)
        lines.append(f"multi-tenant scheduling {'.' * 25} "
                     f"{f'{nt} tenant(s) configured' if nt else 'single lane (no tenants block)'}")
        # multi-LoRA serving: whether the default config builds an adapter
        # registry, the boot-scan dir (DS_ADAPTERS_DIR override), and the
        # bank geometry hot loads must fit inside
        ad = ecfg.adapters
        ad_dir = os.environ.get("DS_ADAPTERS_DIR") or ad.registry_dir
        if ad.enabled:
            state = (f"enabled ({ad.max_live_adapters} slots, "
                     f"rank pad {ad.slot_rank_pad}, "
                     f"targets {','.join(ad.targets)})")
        else:
            state = "disabled (adapters.enabled)"
        lines.append(f"multi-LoRA adapters {'.' * 29} {state}")
        lines.append(f"adapter registry dir {'.' * 28} "
                     f"{ad_dir if ad_dir else 'unset (load via POST /adapters/load)'}")
    except Exception as e:  # pragma: no cover
        lines.append(f"prefix cache {'.' * 36} {NO} ({e})")
    try:
        # durable serving: where the write-ahead request journal would land
        # (env/XDG resolution) and whether that directory is writable — the
        # first thing to check when warm restart isn't replaying anything
        from .inference.v2.journal import journal_dir
        jd = journal_dir()
        writable = os.access(jd if os.path.isdir(jd)
                             else os.path.dirname(jd) or ".", os.W_OK)
        lines.append(f"serving journal dir {'.' * 29} "
                     f"{jd} [{'writable' if writable else 'NOT writable'}]")
    except Exception as e:  # pragma: no cover
        lines.append(f"serving journal dir {'.' * 29} {NO} ({e})")
    try:
        # observability: registry/tracer defaults and where an on-demand
        # jax.profiler capture would land (and whether that dir is writable)
        from .inference.v2.config_v2 import ObservabilityConfig
        from .observability import profile_dir
        ocfg = ObservabilityConfig()
        pd = profile_dir(ocfg.profile_dir)
        writable = os.access(pd if os.path.isdir(pd)
                             else os.path.dirname(pd) or ".", os.W_OK)
        state = ("enabled" if ocfg.enabled else "disabled")
        lines.append(
            f"serving observability {'.' * 27} {state} "
            f"(trace rings {ocfg.trace_requests} req x "
            f"{ocfg.trace_spans_per_request} spans, {ocfg.trace_waves} waves)")
        lines.append(f"profiler capture dir {'.' * 28} "
                     f"{pd} [{'writable' if writable else 'NOT writable'}]")
    except Exception as e:  # pragma: no cover
        lines.append(f"serving observability {'.' * 27} {NO} ({e})")
    try:
        # training observability: which recorders ride the training loop
        # (compile watch / goodput ledger / MFU / memory gauges) and where
        # the Prometheus textfile would land — config > env > disabled
        from .config.feature_configs import TrainObservabilityConfig
        tcfg = TrainObservabilityConfig()
        if tcfg.enabled:
            parts = [n for n, on in (("goodput", tcfg.goodput),
                                     ("compile-watch", tcfg.compile_watch),
                                     ("mfu", tcfg.mfu),
                                     ("memory", tcfg.memory)) if on]
            state = "enabled (" + ", ".join(parts) + ")"
        else:
            state = "disabled"
        lines.append(f"training observability {'.' * 26} {state}")
        tf = tcfg.textfile or os.environ.get("DS_TPU_METRICS_TEXTFILE")
        if tf:
            d = os.path.dirname(os.path.abspath(tf)) or "."
            writable = os.access(d if os.path.isdir(d) else ".", os.W_OK)
            lines.append(f"metrics textfile {'.' * 32} "
                         f"{tf} [{'writable' if writable else 'NOT writable'}]")
        else:
            lines.append(f"metrics textfile {'.' * 32} "
                         f"disabled (set observability.textfile or "
                         f"DS_TPU_METRICS_TEXTFILE)")
    except Exception as e:  # pragma: no cover
        lines.append(f"training observability {'.' * 26} {NO} ({e})")
    try:
        # ZeRO defaults: configured stage and the wire dtype a scheduled
        # stage-3 param gather would move (int8 iff zero_quantized_weights)
        from .config.feature_configs import ZeroConfig
        zc = ZeroConfig()
        lines.append(f"zero stage (default) {'.' * 28} {zc.stage}")
        wire = "int8" if zc.zero_quantized_weights else "fp32"
        lines.append(f"zero3 gather wire dtype {'.' * 25} {wire} "
                     f"(persistence threshold "
                     f"{int(zc.param_persistence_threshold)} elems)")
    except Exception as e:  # pragma: no cover
        lines.append(f"zero defaults {'.' * 35} {NO} ({e})")
    try:
        # disaggregated serving: what the default-config planner would
        # carve THIS host's devices into — the group topology a
        # ``--disagg`` daemon would serve with, or the fallback reason
        from .inference.v2.config_v2 import DisaggregationConfig
        from .inference.v2.disagg import plan_groups
        dcfg = DisaggregationConfig(enabled=True)
        plan = plan_groups(dcfg)
        if plan is not None:
            lines.append(
                f"disagg group topology {'.' * 27} prefill "
                f"{[d.id for d in plan.prefill_devices]} "
                f"(tp={plan.prefill_tp}) | decode "
                f"{[d.id for d in plan.decode_devices]}")
        else:
            lines.append(
                f"disagg group topology {'.' * 27} single group "
                f"({len(jax.local_devices())} device(s) — continuous-"
                f"fusion fallback)")
    except Exception as e:  # pragma: no cover
        lines.append(f"disagg group topology {'.' * 27} {NO} ({e})")
    try:
        devs = jax.devices()
        lines.append(f"platform {'.' * 40} {devs[0].platform}")
        lines.append(f"device count {'.' * 36} {len(devs)}")
        lines.append(f"process count {'.' * 35} {jax.process_count()}")
    except Exception as e:
        lines.append(f"jax devices {'.' * 37} {NO} ({e})")
    return "\n".join(lines)


def main():
    print(op_report())
    print(debug_report())
    return 0


def cli_main():
    sys.exit(main())


if __name__ == "__main__":
    main()
