"""TPU accelerator (the concrete device seam).

Reference: ``accelerator/cuda_accelerator.py`` shape, implemented over jax:
memory stats from the PJRT allocator, synchronize as block-until-ready on a
trivial computation, "pinned" host staging as page-aligned numpy (what our
AIO layer consumes), op lookup through the op registry."""

import os
from typing import Optional

import numpy as np

from .abstract_accelerator import DeepSpeedAccelerator


class _PinnedArray(np.ndarray):
    """ndarray subclass so the aligned view can carry its base allocation."""


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._seed = 0

    def _jax(self):
        import jax
        return jax

    def _device(self, device_index=None):
        devs = self._jax().local_devices()
        return devs[device_index or 0]

    # ---- device ----
    def device_name(self, device_index=None):
        return "tpu" if device_index is None else f"tpu:{device_index}"

    def device_count(self):
        return self._jax().device_count()

    def current_device(self):
        return 0

    def current_device_name(self):
        plat = self._jax().default_backend()
        return f"{plat}:0"

    def is_available(self):
        try:
            return len(self._jax().devices()) > 0
        except Exception:
            return False

    def synchronize(self, device_index=None):
        jax = self._jax()
        jax.block_until_ready(jax.device_put(np.zeros(1), self._device(device_index)))

    # ---- RNG ----
    def manual_seed(self, seed):
        self._seed = int(seed)
        return self._jax().random.PRNGKey(self._seed)

    def initial_seed(self):
        return self._seed

    # ---- memory ----
    def _stats(self, device_index=None) -> dict:
        try:
            return self._device(device_index).memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index=None):
        return self._stats(device_index).get("bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self._stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self._stats(device_index)
        return s.get("bytes_limit", 0) - s.get("bytes_in_use", 0)

    def memory_stats(self, device_index=None):
        return self._stats(device_index)

    def peak_bf16_flops(self, device_index=None) -> float:
        """Per-chip bf16 peak for MFU accounting, keyed on device_kind.
        Published peaks: v4 275, v5e 197, v5p 459, v6e (Trillium) 918
        TFLOP/s. MFU = achieved/peak, so over-claiming requires a peak
        that is too SMALL — an unknown kind therefore falls back to the
        LARGEST known peak (under-claims on slower chips, never inflates)
        with a logged warning. Table order matters: 'v5 lite' must match
        before the bare 'v5' (plain 'TPU v5' is how v5p can report)."""
        from ..utils.logging import logger
        dev = self._device(device_index)
        if getattr(dev, "platform", "") not in ("tpu", "axon"):
            # host-CPU diagnostic runs: no chip, no kind to key on — use the
            # ABC default silently (the numbers are flagged DIAGNOSTIC anyway)
            return super().peak_bf16_flops(device_index)
        kind = (getattr(dev, "device_kind", "") or "").lower()
        table = {"v6": 918e12, "v5p": 459e12, "v5 lite": 197e12,
                 "v5e": 197e12, "v5": 459e12, "v4": 275e12}
        for key, peak in table.items():
            if key in kind:
                return peak
        logger.warning(f"unknown TPU device_kind {kind!r}: assuming the "
                       f"largest known peak (918 TF/s) so MFU is never "
                       f"over-claimed")
        return 918e12

    # ---- dtypes ----
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True  # supported, but bf16 is the native fast path

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ---- pinned host memory (AIO staging) ----
    def pin_memory(self, tensor, align_bytes=4096):
        """Page-aligned host copy (what O_DIRECT AIO wants)."""
        from ..ops.aio import aligned_empty  # one owner of the alignment trick
        arr = np.asarray(tensor)
        aligned = aligned_empty(arr.nbytes, align_bytes).view(
            arr.dtype).reshape(arr.shape).view(_PinnedArray)
        aligned[...] = arr
        return aligned

    def is_pinned(self, tensor):
        return isinstance(tensor, _PinnedArray) or (
            hasattr(tensor, "ctypes") and tensor.ctypes.data % 4096 == 0)

    # ---- ops ----
    def create_op_builder(self, op_name):
        return self.get_op_builder(op_name)

    def get_op_builder(self, op_name):
        from ..ops.registry import registry
        report = registry.report()
        return report.get(op_name)

    def op_report(self):
        from ..ops.registry import op_report
        return op_report()
