"""Accelerator abstraction.

Reference: ``accelerator/abstract_accelerator.py:10 DeepSpeedAccelerator``
— an ~80-method ABC because torch exposes device state imperatively
(streams, events, RNG, allocator). Under XLA most of that surface is owned
by the compiler, so the TPU ABC keeps the *decision points* that still
exist: device identity/counts, memory stats, dtype support, RNG seeding,
synchronization, host ("pinned") staging buffers, the communication-backend
name, and op lookup. Stream/event methods exist as no-op shims for ported
callers (XLA orders work by data dependence + donation; there is nothing to
schedule by hand)."""

import abc


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ---- device ----
    @abc.abstractmethod
    def device_name(self, device_index=None): ...

    @abc.abstractmethod
    def device_count(self): ...

    @abc.abstractmethod
    def current_device(self): ...

    @abc.abstractmethod
    def current_device_name(self): ...

    def set_device(self, device_index):  # processes own all local chips
        return None

    @abc.abstractmethod
    def is_available(self): ...

    @abc.abstractmethod
    def synchronize(self, device_index=None): ...

    # ---- RNG ----
    @abc.abstractmethod
    def manual_seed(self, seed): ...

    @abc.abstractmethod
    def initial_seed(self): ...

    # ---- memory ----
    @abc.abstractmethod
    def memory_allocated(self, device_index=None): ...

    @abc.abstractmethod
    def total_memory(self, device_index=None): ...

    @abc.abstractmethod
    def available_memory(self, device_index=None): ...

    def memory_stats(self, device_index=None):
        return {}

    def empty_cache(self):
        return None

    # ---- dtype support ----
    def peak_bf16_flops(self, device_index=None) -> float:
        """Per-chip bf16 peak for MFU accounting. Default is v5e's figure;
        accelerator flavors override with device_kind-aware values (see
        TPU_Accelerator). Part of the public surface — bench/profiling
        call this through get_accelerator()."""
        return 197e12

    @abc.abstractmethod
    def is_bf16_supported(self): ...

    @abc.abstractmethod
    def is_fp16_supported(self): ...

    @abc.abstractmethod
    def supported_dtypes(self): ...

    # ---- comm ----
    def communication_backend_name(self):
        return self._communication_backend_name

    # ---- host staging ("pinned") memory ----
    @abc.abstractmethod
    def pin_memory(self, tensor, align_bytes=1): ...

    @abc.abstractmethod
    def is_pinned(self, tensor): ...

    # ---- ops ----
    @abc.abstractmethod
    def create_op_builder(self, op_name): ...

    @abc.abstractmethod
    def get_op_builder(self, op_name): ...

    # ---- stream/event shims (XLA owns scheduling) ----
    def stream(self, stream):
        import contextlib
        return contextlib.nullcontext()

    def current_stream(self, device_index=None):
        return None

    def default_stream(self, device_index=None):
        return None

    def create_event(self, **kwargs):
        return None
