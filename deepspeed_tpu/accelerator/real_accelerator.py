"""Accelerator singleton.

Reference: ``accelerator/real_accelerator.py:51 get_accelerator`` /
``:207 set_accelerator`` — env override (``DS_ACCELERATOR``) then probe.
On this stack "tpu" covers the XLA device whatever the backend reports
(tpu/cpu/gpu); a CPU-flavored instance exists only so tests can assert the
env-override path."""

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator
from .tpu_accelerator import TPU_Accelerator

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None


class CPU_Accelerator(TPU_Accelerator):
    """XLA-on-CPU flavor (DS_ACCELERATOR=cpu); same mechanics via jax."""

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "gloo"  # reference cpu default name

    def device_name(self, device_index=None):
        return "cpu" if device_index is None else f"cpu:{device_index}"


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        name = os.environ.get("DS_ACCELERATOR", "tpu").lower()
        _ACCELERATOR = CPU_Accelerator() if name == "cpu" else TPU_Accelerator()
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator().is_available()
