"""Shared JIT build scheme for the C++ host libraries.

Reference: ``op_builder/builder.py:535 jit_load`` — compile-on-first-use with
a cached artifact. Here the artifact name embeds a content hash of the source
(mtime gating is timestamp-dependent after a fresh clone), the compile goes
through a temp file + ``os.replace`` so an interrupted or concurrent build
can never leave a corrupt .so at the final path, and artifacts from older
source revisions are purged.
"""

import hashlib
import os
import subprocess
from typing import List, Optional

from ..utils.logging import logger


def jit_build(src: str, libname: str, extra_flags: Optional[List[str]] = None) -> str:
    """Compile ``src`` into ``<srcdir>/build/<libname>-<hash>.so`` if absent;
    returns the .so path. Raises CalledProcessError/OSError on failure."""
    build_dir = os.environ.get("DS_TPU_BUILD_DIR",
                               os.path.join(os.path.dirname(src), "build"))
    with open(src, "rb") as f:
        src_hash = hashlib.sha256(f.read()).hexdigest()[:12]
    so_path = os.path.join(build_dir, f"{libname}-{src_hash}.so")
    if not os.path.exists(so_path):
        os.makedirs(build_dir, exist_ok=True)
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               *(extra_flags or []), src, "-o", tmp_path]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp_path, so_path)  # atomic: losers overwrite with identical bits
        finally:
            if os.path.exists(tmp_path):
                try:
                    os.remove(tmp_path)
                except OSError:
                    pass
        logger.info(f"built {so_path}")
        for name in os.listdir(build_dir):
            full = os.path.join(build_dir, name)
            if (name.startswith(libname) and name.endswith(".so") and full != so_path):
                try:
                    os.remove(full)
                except OSError:
                    pass
    return so_path
