"""Pallas paged-attention (blocked flash decode) for the ragged engine.

Reference capability: ``deepspeed/inference/v2/kernels/ragged_ops/
blocked_flash/`` (attention_atom.h — per-atom block-table flash over a paged
KV cache). TPU design, rather than a port of the CUDA atom machinery:

- Grid ``(seqs, pages)``: ONE grid step streams one whole KV page — ALL
  heads — against every query head (static in-kernel head unroll). The
  page loop is innermost so an online softmax (running max / sum /
  accumulator in VMEM scratch) streams the sequence's history one page at
  a time; no [S, L, ...] gather is ever materialized. The earlier design
  put kv_heads in the grid: 16x the grid steps, 16x smaller DMAs, and the
  8/1 xprof trace showed per-step overheads dominating exactly that shape.
- The *block table is scalar-prefetched*: the BlockSpec index map reads
  ``block_table[s, page]`` to DMA exactly the pages the sequence owns,
  straight from the full cache in HBM — the layer index is prefetched too,
  so the cache is never sliced per layer (which would copy).
- Pages past a sequence's length clamp to the previous page id: Pallas skips
  the re-fetch of an identical block, so short sequences don't pay the
  bucketed page count in bandwidth.
- GQA is native: queries arrive ``[S, N, H, D]`` with H = KV*G in kv-major
  order (the natural q head order) and each kv head's G query rows contract
  against its page slice — KV is never expanded to Q heads.
- Sliding-window (Mistral local attention) masks in-kernel and SKIPS pages
  entirely older than the window; ALiBi (BLOOM) adds the per-head slope bias
  to the scores in the ``[N, G, page]`` view (no gathers); ``attn_scale``
  overrides 1/sqrt(D) (GPT-Neo uses 1.0).

Cache layout: ``[2*layers, num_slots, kv_heads*head_dim]`` with k at row
``2l``, v at row ``2l+1`` and ``num_slots = num_pages * page_size``. This is
the SCATTER-NATIVE layout: the model's per-token KV append is a single
in-place donated scatter along the slot dim (the earlier
``[L, 2, KV, slots, D]`` layout made XLA materialize TWO transposed copies
of the entire cache per forward — 2.01 GB of HLO temps on a 1 GB cache,
measured 8/1; the 32k-context serving sweep OOMed on exactly that copy).
The kernel views it as ``[2L, num_pages, page_size, KV*D]`` (a free
middle-dim reshape) and DMAs one ``(2, page_size, KV*D)`` k+v page block
per (layer, page) — every block's minor dims are (sublane mult-of-8,
lane == array dim), the Mosaic-legal pattern; per-head slices inside the
kernel are STATIC lane offsets.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover - pallas-less jax installs
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _paged_attn_kernel(layer_ref, bt_ref, seen_ref, lens_ref,  # scalar prefetch
                       q_ref, kv_ref, *rest,
                       page_size: int, num_kv: int, groups: int, scale: float,
                       window: Optional[int], has_alibi: bool,
                       softcap: Optional[float] = None,
                       has_scales: bool = False):
    rest = list(rest)
    scales_ref = rest.pop(0) if has_scales else None
    slopes_ref = rest.pop(0) if has_alibi else None
    o_ref, m_scr, l_scr, acc_scr = rest
    s = pl.program_id(0)
    b = pl.program_id(1)
    n_pages = pl.num_programs(1)
    D = q_ref.shape[-1]
    N = q_ref.shape[1]
    ng = N * groups

    @pl.when(b == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    hist_len = lens_ref[s]   # seen + new: valid key region
    seen = seen_ref[s]

    live = b * page_size < hist_len
    if window is not None:
        # the whole page is older than the window for EVERY query row
        # (earliest query is at absolute position `seen`)
        live = live & ((b + 1) * page_size - 1 > seen - window)

    @pl.when(live)
    def _accumulate():
        # q block: [1, N, H, D]; kv block: [2, 1, page, KV*D]. Operands
        # stay in the cache dtype: the MXU fast path is bf16 x bf16 with
        # fp32 accumulation (preferred_element_type); pre-casting to fp32
        # would run the dots several-fold slower.
        q_all = q_ref[0]  # [N, H, D]
        # positional masks are shared by every head — build once per page
        key_pos1 = b * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (ng, page_size), 1)
        q_abs1 = seen + jax.lax.broadcasted_iota(
            jnp.int32, (ng, page_size), 0) // groups
        mask = (key_pos1 <= q_abs1) & (key_pos1 < hist_len)
        if window is not None:
            mask &= key_pos1 > q_abs1 - window
        for h in range(num_kv):  # static unroll: one page DMA, all heads
            q = q_all[:, h * groups:(h + 1) * groups, :].reshape(ng, D)
            k = kv_ref[0, 0, :, h * D:(h + 1) * D]  # [page, D] static slice
            v = kv_ref[1, 0, :, h * D:(h + 1) * D]
            if has_scales:
                # int8 KV: dequantize the page in-registers (per-slot-
                # vector scales, [page, 1] slice broadcast over head_dim)
                k = k.astype(jnp.bfloat16) * \
                    scales_ref[0, 0, :, h:h + 1].astype(jnp.bfloat16)
                v = v.astype(jnp.bfloat16) * \
                    scales_ref[1, 0, :, h:h + 1].astype(jnp.bfloat16)

            scores = jax.lax.dot_general(
                q, k, (((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [NG, page]
            if softcap is not None:  # Gemma-2: cap BEFORE masks/bias
                from .attention import softcap_scores
                scores = softcap_scores(scores, softcap)
            if has_alibi:
                # [N, G, page] view: slope varies over G, distance (N, page)
                s3 = scores.reshape(N, groups, page_size)
                kp3 = b * page_size + jax.lax.broadcasted_iota(
                    jnp.int32, s3.shape, 2)
                qa3 = seen + jax.lax.broadcasted_iota(jnp.int32, s3.shape, 0)
                bias = slopes_ref[0, h][None, :, None] * \
                    (kp3 - qa3).astype(jnp.float32)
                scores = (s3 + bias).reshape(ng, page_size)

            r = slice(h * ng, (h + 1) * ng)  # this head's scratch rows
            m_prev = m_scr[r]
            l_prev = l_scr[r]
            masked = jnp.where(mask, scores, NEG_INF)
            m_new = jnp.maximum(m_prev, jnp.max(masked, axis=-1,
                                                keepdims=True))
            # keep the running max finite so exp() never sees inf-inf
            m_new = jnp.maximum(m_new, NEG_INF)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(mask, jnp.exp(masked - m_new), 0.0)  # [NG, page]
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[r] = acc_scr[r] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[r] = m_new
            l_scr[r] = l_new

    @pl.when(b == n_pages - 1)
    def _finalize():
        for h in range(num_kv):
            r = slice(h * ng, (h + 1) * ng)
            l = l_scr[r]  # [NG, 1]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            out = jnp.where(l > 0, acc_scr[r] / safe_l, 0.0)
            o_ref[0, :, h * groups:(h + 1) * groups, :] = \
                out.reshape(N, groups, D).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret", "window",
                                             "attn_scale", "use_alibi",
                                             "softcap"))
def paged_attention(q, cache, layer, block_table, seq_seen, seq_lens,
                    *, page_size: int, interpret: bool = False,
                    window: Optional[int] = None,
                    attn_scale: Optional[float] = None,
                    use_alibi: bool = False,
                    slopes=None,
                    cache_scales=None,
                    softcap: Optional[float] = None):
    """Blocked-flash attention over a paged KV cache.

    Args:
      q: ``[S, N, H, D]`` queries (N new tokens per sequence; H = KV*G in
        the natural kv-major head order).
      cache: ``[2L, num_slots, KV*D]`` full paged cache (k row 2l, v row
        2l+1; never sliced — see module docstring for why this layout).
      layer: scalar int — which layer's pages to read.
      block_table: ``[S, B]`` int32 page ids per sequence.
      seq_seen: ``[S]`` history length before this step.
      seq_lens: ``[S]`` seen + n_new (valid key region).
      window: sliding-window size (None = global); ``attn_scale`` overrides
      1/sqrt(D); ``use_alibi`` adds BLOOM-style slope bias per query head.
      slopes: optional explicit ``[KV, G]`` ALiBi slopes (implies alibi) —
      under TP the caller passes each shard its GLOBAL-head slice (reference
      sharding/attn.py keeps head identity across shards); None derives them
      from local head indices, correct only unsharded.
      cache_scales: optional ``[2L, num_slots, KV]`` per-slot-vector
      dequant scales for an int8 ``cache`` — pages dequantize in-kernel.
    Returns:
      ``[S, N, H, D]`` in q.dtype.
    """
    S, N, H, D = q.shape
    B = block_table.shape[1]
    L2, slots, KVD = cache.shape
    KV = KVD // D
    G = H // KV
    scale = attn_scale if attn_scale is not None else 1.0 / (D ** 0.5)
    n_pages = slots // page_size
    # free reshape (middle-dim split): one (layer, page) DMA block is
    # [2, page_size, KV*D] — k and v pages for every head arrive together
    kv_pages = cache.reshape(L2, n_pages, page_size, KVD)

    def q_map(s, b, layer_r, bt_r, seen_r, lens_r):
        return (s, 0, 0, 0)

    def kv_map(s, b, layer_r, bt_r, seen_r, lens_r):
        # clamp trailing pages to the last needed page: identical consecutive
        # block indices skip the DMA re-fetch
        needed = jax.lax.max((lens_r[s] + page_size - 1) // page_size, 1)
        page = bt_r[s, jax.lax.min(b, needed - 1)]
        return (layer_r[0], page, 0, 0)

    def o_map(s, b, layer_r, bt_r, seen_r, lens_r):
        return (s, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, N, H, D), q_map),
        pl.BlockSpec((2, 1, page_size, KVD), kv_map),
    ]
    inputs = [q, kv_pages]
    has_scales = cache_scales is not None
    if has_scales:
        # scales ride the SAME page lookup as their kv page (kv_map, one
        # copy of the clamp): [2L, slots, KV] viewed as [2L, n_pages, page,
        # KV] — block minor dims (page, KV) are (mult-of-8 sublane,
        # lane == array dim), Mosaic-legal
        in_specs.append(pl.BlockSpec((2, 1, page_size, KV), kv_map))
        inputs.append(cache_scales.reshape(L2, n_pages, page_size, KV))
    has_alibi = use_alibi or slopes is not None
    if has_alibi:
        if slopes is None:
            from ..models.llama import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(H)).reshape(KV, G)
        # [1, KV, G] with block (1, KV, G): the last two block dims equal
        # the array dims, which Mosaic lowers for any KV/G
        in_specs.append(pl.BlockSpec((1, KV, G), lambda s, b, *_: (0, 0, 0)))
        inputs.append(slopes.astype(jnp.float32).reshape(1, KV, G))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, B),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, N, H, D), o_map),
        scratch_shapes=[
            # rows grouped kv-head-major: head h owns [h*NG, (h+1)*NG)
            pltpu.VMEM((N * H, 1), jnp.float32),  # running max
            pltpu.VMEM((N * H, 1), jnp.float32),  # running sum
            pltpu.VMEM((N * H, D), jnp.float32),  # accumulator
        ],
    )

    kernel = functools.partial(_paged_attn_kernel, page_size=page_size,
                               num_kv=KV, groups=G, scale=scale,
                               window=window, softcap=softcap,
                               has_alibi=has_alibi, has_scales=has_scales)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, N, H, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray([layer], jnp.int32), block_table.astype(jnp.int32),
      seq_seen.astype(jnp.int32), seq_lens.astype(jnp.int32), *inputs)


def paged_attention_reference(q, cache, layer, block_table, seq_seen, seq_lens,
                              *, page_size: int, window: Optional[int] = None,
                              attn_scale: Optional[float] = None,
                              use_alibi: bool = False,
                              slopes=None,
                              cache_scales=None,
                              softcap: Optional[float] = None):
    """Dense-gather XLA reference (the round-1 path) for numerics tests."""
    S, N, H, D = q.shape
    B = block_table.shape[1]
    L = B * page_size
    KV = cache.shape[-1] // D
    G = H // KV
    scale = attn_scale if attn_scale is not None else 1.0 / (D ** 0.5)
    j = jnp.arange(L, dtype=jnp.int32)
    slot_grid = block_table[:, j // page_size] * page_size + j % page_size
    # cache [2L, slots, KV*D]: gather the window rows, unfold the head dim
    k_h = cache[2 * layer][slot_grid].reshape(S, L, KV, D)    # [S, L, KV, D]
    v_h = cache[2 * layer + 1][slot_grid].reshape(S, L, KV, D)
    if cache_scales is not None:  # int8 cache: dequant the gathered window
        k_sc = cache_scales[2 * layer][slot_grid]             # [S, L, KV]
        v_sc = cache_scales[2 * layer + 1][slot_grid]
        k_h = k_h.astype(jnp.float32) * k_sc[..., None].astype(jnp.float32)
        v_h = v_h.astype(jnp.float32) * v_sc[..., None].astype(jnp.float32)
    k_h = jnp.moveaxis(k_h, 2, 1).astype(jnp.float32)          # [S, KV, L, D]
    v_h = jnp.moveaxis(v_h, 2, 1).astype(jnp.float32)
    qf = q.reshape(S, N, KV, G, D).astype(jnp.float32)
    scores = jnp.einsum("snkgd,skld->snkgl", qf, k_h) * scale
    if softcap is not None:
        from .attention import softcap_scores
        scores = softcap_scores(scores, softcap)
    key_pos = jnp.arange(L, dtype=jnp.int32)[None, None, :]
    q_abs = seq_seen[:, None] + jnp.arange(N, dtype=jnp.int32)[None, :]
    mask = (key_pos <= q_abs[:, :, None]) & (key_pos < seq_lens[:, None, None])
    if window is not None:
        mask &= key_pos > q_abs[:, :, None] - window
    if use_alibi or slopes is not None:
        if slopes is None:
            from ..models.llama import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(H)).reshape(KV, G)
        dist = (key_pos[:, :, None, None, :]
                - q_abs[:, :, None, None, None]).astype(jnp.float32)
        scores = scores + slopes[None, None, :, :, None].astype(jnp.float32) * dist
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    any_visible = mask.any(-1)[:, :, None, None, None]
    out = jnp.einsum("snkgl,skld->snkgd", probs, v_h)
    return jnp.where(any_visible, out, 0.0).reshape(S, N, H, D).astype(q.dtype)


from .registry import registry  # noqa: E402

registry.register("paged_attention", "pallas" if _HAS_PLTPU else "xla", True,
                  "ragged blocked-flash decode over paged KV (block tables, "
                  "window/ALiBi/scale in-kernel; reference ragged_ops)")
