"""ctypes binding for the C++ SIMD host optimizers.

Reference: ``op_builder/cpu_adam.py`` + ``csrc/adam/cpu_adam_impl.cpp``
(AVX Step_AVX), ``csrc/adagrad``, ``csrc/lion`` — here one translation unit
(``csrc/cpu_optim/cpu_optim.cpp``) auto-vectorized with -O3 -march=native
-fopenmp, built JIT with the same content-hashed artifact scheme as the AIO
lib. Falls back to the numpy implementations in ``runtime/host_offload.py``
when no toolchain is present.
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..utils.logging import logger
from .jit_build import jit_build
from .registry import registry

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "cpu_optim", "cpu_optim.cpp")
_lib = None
_build_failed = False
_lock = threading.Lock()

_F32P = ctypes.POINTER(ctypes.c_float)


def _jit_load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:  # lock-free fast path: called per leaf per step
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            so_path = jit_build(_SRC, "libds_cpu_optim", ["-march=native", "-fopenmp"])
            lib = ctypes.CDLL(so_path)
            lib.ds_adam_step.argtypes = [_F32P, _F32P, _F32P, _F32P,
                                         ctypes.c_int64, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_int, ctypes.c_int64]
            lib.ds_adagrad_step.argtypes = [_F32P, _F32P, _F32P, ctypes.c_int64,
                                            ctypes.c_float, ctypes.c_float]
            lib.ds_lion_step.argtypes = [_F32P, _F32P, _F32P, ctypes.c_int64,
                                         ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float]
            _lib = lib
            registry.register("cpu_optim", "native", True)
        except (subprocess.CalledProcessError, OSError) as e:
            logger.warning(f"cpu_optim native build unavailable ({e}); "
                           "numpy host optimizers will be used")
            _build_failed = True
            registry.register("cpu_optim", "fallback", True)
        return _lib


def cpu_optim_available() -> bool:
    return _jit_load() is not None


def _ptr(a: np.ndarray):
    # hard error, not assert: a wrong-dtype buffer reinterpreted by the C
    # kernel silently corrupts parameters (and -O strips asserts)
    if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"]:
        raise ValueError(f"expected C-contiguous float32 array, got dtype={a.dtype} "
                         f"contiguous={a.flags['C_CONTIGUOUS']}")
    return a.ctypes.data_as(_F32P)


def adam_step(p, g, m, v, *, lr, b1, b2, eps, wd, adamw, step) -> bool:
    """In-place fused AdamW step; returns False if the native lib is absent
    (caller falls back to numpy)."""
    lib = _jit_load()
    if lib is None:
        return False
    g = np.ascontiguousarray(g, np.float32)
    lib.ds_adam_step(_ptr(p), _ptr(g), _ptr(m), _ptr(v), p.size,
                     lr, b1, b2, eps, wd, int(adamw), step)
    return True


def adagrad_step(p, g, accum, *, lr, eps) -> bool:
    lib = _jit_load()
    if lib is None:
        return False
    g = np.ascontiguousarray(g, np.float32)
    lib.ds_adagrad_step(_ptr(p), _ptr(g), _ptr(accum), p.size, lr, eps)
    return True


def lion_step(p, g, m, *, lr, b1, b2, wd) -> bool:
    lib = _jit_load()
    if lib is None:
        return False
    g = np.ascontiguousarray(g, np.float32)
    lib.ds_lion_step(_ptr(p), _ptr(g), _ptr(m), p.size, lr, b1, b2, wd)
    return True
