"""Persistent on-disk autotune cache for per-shape kernel decisions.

One JSON table maps a *shape signature* (leg + shape + dtype + mask flags +
device kind, see ``kernel_dispatch.signature``) to the kernel implementation
and (block_q, block_k) that measured fastest for it.  The offline sweep tool
(``tests/perf/run_attn_sweep.py`` / ``bin/ds_kernel_tune``) is the writer;
``kernel_dispatch.resolve`` is the reader.  When no measurement exists for a
signature the dispatcher falls back to its built-in heuristic table — the
cache only ever *upgrades* a decision, never blocks one.

File format (version-stamped so a schema change can invalidate old tables)::

    {"version": 1,
     "entries": {"<signature>": {"impl": "xla|pallas|folded",
                                 "block_q": 512, "block_k": 1024,
                                 "ms": 42.7, "utc": "...", "note": "..."}}}

Durability follows the checkpoint layer's commit idiom (tmp + fsync +
rename): a writer killed mid-commit leaves either the old table or the new
one, never truncated JSON.  A corrupt/unreadable table degrades to "no
measurements" — dispatch still works off the heuristics.

Location precedence (env wins, mirroring ``$DS_TPU_COMPILE_CACHE_DIR``):
``$DS_TPU_ATTN_CACHE_DIR``/attn_dispatch.json if the env is set, else
``$XDG_CACHE_HOME|~/.cache``/deepspeed_tpu/attn_dispatch.json.  Never a
repo-relative dotfile (tier-1 CI points the env at a hermetic temp dir).
"""

import json
import os
import time
from typing import Dict, Optional

CACHE_VERSION = 1
CACHE_FILENAME = "attn_dispatch.json"


def cache_dir() -> str:
    """Directory holding the dispatch table — ``$DS_TPU_ATTN_CACHE_DIR`` if
    set, else the per-user XDG cache tree (outside any repo checkout)."""
    env = os.environ.get("DS_TPU_ATTN_CACHE_DIR")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "deepspeed_tpu")


def cache_path() -> str:
    return os.path.join(cache_dir(), CACHE_FILENAME)


def _load_table(path: str) -> Dict:
    """Parse the table at ``path``; any failure (missing, torn, wrong
    version) reads as an empty table — measurements are an optimization,
    never a dependency."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("version") != CACHE_VERSION:
        return {}
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


class AutotuneCache:
    """mtime-validated view over the on-disk table plus the commit writer."""

    def __init__(self, path: Optional[str] = None):
        self._explicit_path = path
        self._loaded_for = None  # (path, mtime) the in-memory table mirrors
        self._entries: Dict[str, Dict] = {}

    @property
    def path(self) -> str:
        return self._explicit_path or cache_path()

    def _refresh(self) -> None:
        path = self.path
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            mtime = None
        key = (path, mtime)
        if key == self._loaded_for:
            return
        self._entries = _load_table(path) if mtime is not None else {}
        self._loaded_for = key

    def lookup(self, signature: str) -> Optional[Dict]:
        self._refresh()
        ent = self._entries.get(signature)
        return dict(ent) if isinstance(ent, dict) else None

    def entries(self) -> Dict[str, Dict]:
        self._refresh()
        return dict(self._entries)

    def commit(self, signature: str, entry: Dict) -> None:
        """Merge one measured winner into the table and atomically replace
        it (tmp/fsync/rename — same crash-consistency contract as the
        checkpoint layer's manifest writer)."""
        path = self.path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        entries = _load_table(path)
        entries[signature] = dict(entry,
                                  utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                    time.gmtime()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": CACHE_VERSION, "entries": entries}, f,
                      indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._loaded_for = None  # next lookup re-reads the committed table

    def source_description(self) -> str:
        """Human line for ds_report: where decisions come from right now."""
        self._refresh()
        if self._entries:
            return f"measured ({self.path}, {len(self._entries)} entries)"
        return f"heuristic (no measured table at {self.path})"


_default_cache: Optional[AutotuneCache] = None


def get_cache() -> AutotuneCache:
    """Process-wide cache view.  The path is re-resolved inside ``_refresh``
    on every lookup, so a test that monkeypatches ``DS_TPU_ATTN_CACHE_DIR``
    gets its hermetic table without touching module state."""
    global _default_cache
    if _default_cache is None:
        _default_cache = AutotuneCache()
    return _default_cache
