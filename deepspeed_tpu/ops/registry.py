"""Op registry + compatibility report.

Analog of reference ``op_builder/builder.py`` (``OpBuilder.is_compatible``,
``ds_report`` CLI): ops register themselves with a name, the backend they
use on this platform ("pallas" | "xla"), and whether the fast path is
available. There is no JIT compilation of extensions — Pallas kernels compile
through XLA at trace time — so "installed" vs "compatible" collapses to one
availability probe.
"""

import functools
from typing import Callable, Dict, NamedTuple, Optional

import jax


class OpInfo(NamedTuple):
    name: str
    backend: str  # "pallas" or "xla"
    compatible: bool
    reason: str


class OpRegistry:

    def __init__(self):
        self._ops: Dict[str, OpInfo] = {}

    def register(self, name: str, backend: str, compatible: bool, reason: str = ""):
        self._ops[name] = OpInfo(name, backend, compatible, reason)

    def report(self) -> Dict[str, OpInfo]:
        return dict(self._ops)

    def __contains__(self, name):
        return name in self._ops


registry = OpRegistry()


@functools.cache
def on_tpu() -> bool:
    """Canonical is-this-a-TPU probe — EVERY fast-path gate must use this.
    The axon relay registers its PJRT plugin under platform name "axon"
    (not "tpu"), so a bare ``default_backend() == "tpu"`` check silently
    routes real chips onto the XLA fallback paths."""
    try:
        if jax.default_backend() in ("tpu", "axon"):
            return True
        return any(d.platform in ("tpu", "axon") or
                   "TPU" in (getattr(d, "device_kind", "") or "")
                   for d in jax.devices())
    except Exception:
        return False


@functools.cache
def pallas_available() -> bool:
    """Pallas TPU kernels need a TPU backend; interpret mode covers tests."""
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def use_pallas(force: Optional[bool] = None) -> bool:
    """Fast-path decision: pallas on real TPU; XLA elsewhere unless forced
    (tests force interpret mode)."""
    if force is not None:
        return force
    return on_tpu() and pallas_available()


def compatible_ops():
    return [o.name for o in registry.report().values() if o.compatible]


def op_report() -> str:
    """ds_report-style compatibility matrix (reference bin/ds_report)."""
    lines = ["-" * 60, "deepspeed_tpu op compatibility report",
             f"backend: {jax.default_backend()}", "-" * 60,
             f"{'op':<30}{'impl':<10}{'compatible'}"]
    for info in registry.report().values():
        lines.append(f"{info.name:<30}{info.backend:<10}{info.compatible}"
                     + (f"  [{info.reason}]" if info.reason else ""))
    return "\n".join(lines)
