from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
                              BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              VariableSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .splash import splash_sparse_attention, splash_flops, build_block_table
