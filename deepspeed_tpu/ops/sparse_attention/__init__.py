from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
                              BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              VariableSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
