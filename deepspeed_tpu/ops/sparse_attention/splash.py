"""Pallas splash attention: block-sparse attention that SKIPS masked tiles.

Reference: the Triton block-sparse kernels
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD — compute only the
blocks present in the layout — and ``softmax.py`` operating on the packed
block values). The dense-mask fallback in ``sparse_self_attention.py``
computes all S² scores and throws most away; this kernel's grid is
``(batch*heads, q_blocks, max_active)`` where ``max_active`` is the widest
row of the layout — compute AND HBM traffic scale with the number of ACTIVE
blocks, not S².

Mechanism (same scalar-prefetch idiom as ``ops/paged_attention.py``): the
static [H, nb, nb] layout compiles to a block table ``[H, nb, A]`` of active
k-block indices plus per-row counts; the k/v BlockSpec index_map reads the
table (scalar prefetch) so each grid step streams exactly one ACTIVE k/v
block; trailing padded steps are skipped with ``pl.when``. Online softmax
accumulators live in VMEM scratch across the active sweep.

Backward is sparse too (reference parity: the Triton SDD/DSD matmuls of
``matmul.py:63`` are differentiable through the sparse path): a dq kernel
sweeps the same block table as the forward, and a dk/dv kernel sweeps the
TRANSPOSED table (for each k-block, the q-blocks that attend to it), both
recomputing per-tile probabilities from the forward's saved logsumexp — so
backward compute and HBM traffic also scale with active blocks, not S².
"""

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

NEG_INF = -1e30


def build_block_table(layout: np.ndarray):
    """[H, nb, nb] 0/1 layout → (table [H, nb, A] int32, counts [H, nb] int32).

    A = widest active row; padding entries point at block 0 and are skipped
    via the counts.
    """
    layout = np.asarray(layout).astype(bool)
    H, nb, nb2 = layout.shape
    assert nb == nb2, layout.shape
    counts = layout.sum(-1).astype(np.int32)
    A = max(int(counts.max()), 1)
    table = np.zeros((H, nb, A), dtype=np.int32)
    for h in range(H):
        for qb in range(nb):
            idx = np.nonzero(layout[h, qb])[0]
            table[h, qb, :len(idx)] = idx
    return table, counts


def _splash_kernel(table_ref, count_ref, q_ref, k_ref, v_ref, o_ref, *rest,
                   scale, num_active, nheads_layout, with_lse=False):
    if with_lse:
        lse_ref, acc, m_s, l_s = rest
    else:
        acc, m_s, l_s = rest
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ai = pl.program_id(2)
    # bh = batch*H + h; rem by the LAYOUT head count handles both per-head
    # layouts (H) and a single broadcast layout (1)
    h = jax.lax.rem(bh, nheads_layout)

    @pl.when(ai == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    @pl.when(ai < count_ref[h, qi])
    def _compute():
        # operands stay in the input dtype: the MXU fast path is
        # bf16 x bf16 with fp32 accumulation (preferred_element_type);
        # softmax math runs on the fp32 accumulator outputs
        q = q_ref[0]  # [block, D]
        k = k_ref[0]  # [block, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        m_prev, l_prev = m_s[:, 0], l_s[:, 0]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_cur[:, None])
        corr = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - m_cur))
        l_s[:, 0] = l_prev * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv
        m_s[:, 0] = m_cur

    @pl.when(ai == num_active - 1)
    def _finalize():
        l = l_s[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible block → 0
        o_ref[0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)
        if with_lse:
            # +BIG for empty rows so backward's exp(s - lse) underflows to
            # exactly 0 (their grads must be 0, not NaN). lse rides a
            # [BH, S, 1] array: Mosaic requires the last two block dims be
            # (mult-of-8, mult-of-128) or equal to the array dims — a 2-D
            # (1, block) spec over [BH, S] is unlowerable.
            lse_ref[0] = jnp.where(l == 0.0, -NEG_INF,
                                   m_s[:, 0] + jnp.log(safe_l))[:, None]


def _splash_fwd(q, k, v, table, counts, block, scale, interpret,
                with_lse=False):
    if not _HAS_PLTPU:
        raise RuntimeError("splash attention needs jax.experimental.pallas.tpu; "
                           "use sparse_attention(..., use_kernel=False)")
    B, H, S, D = q.shape
    nb = S // block
    A = table.shape[-1]
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)

    kernel = functools.partial(_splash_kernel, scale=scale, num_active=A,
                               nheads_layout=table.shape[0],
                               with_lse=with_lse)
    q_spec = pl.BlockSpec((1, block, D), lambda b, qi, ai, tbl, cnt: (b, qi, 0))
    kv_spec = pl.BlockSpec((1, block, D),
                           lambda b, qi, ai, tbl, cnt:
                           (b, tbl[jax.lax.rem(b, tbl.shape[0]), qi, ai], 0))
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((B * H, S, D), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, block, 1),
                                      lambda b, qi, ai, tbl, cnt: (b, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, nb, A),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=out_specs if with_lse else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((block, D), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape if with_lse else out_shape[0],
        interpret=interpret,
    )(jnp.asarray(table), jnp.asarray(counts), qf, kf, vf)
    if with_lse:
        o, lse = out
        return o.reshape(B, H, S, D), lse
    return out.reshape(B, H, S, D)


def _splash_dq_kernel(table_ref, count_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, acc, *,
                      scale, num_active, nheads_layout):
    """dQ sweep — same block table as forward: for each q-block, iterate its
    active k-blocks; P is recomputed per tile from the saved logsumexp
    (standard flash backward; reference matmul.py SDD backward)."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ai = pl.program_id(2)
    h = jax.lax.rem(bh, nheads_layout)

    @pl.when(ai == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    @pl.when(ai < count_ref[h, qi])
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0])           # lse block is [block, 1]
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale  # delta block is [block, 1]
        acc[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                      (((1, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)

    @pl.when(ai == num_active - 1)
    def _finalize():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _splash_dkv_kernel(tableT_ref, countT_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                       scale, num_active, nheads_layout):
    """dK/dV sweep — TRANSPOSED block table: for each k-block, iterate the
    q-blocks that attend to it (reference matmul.py DSD backward's
    transposed layout)."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    ai = pl.program_id(2)
    h = jax.lax.rem(bh, nheads_layout)

    @pl.when(ai == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(ai < countT_ref[h, ki])
    def _compute():
        q = q_ref[0]   # [block_q, D] — the ai-th active q-block for this k
        k = k_ref[0]   # [block_k, D]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0])                   # [bq, bk]; lse [bq, 1]
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale           # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ai == num_active - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _splash_bwd(q, k, v, o, lse, g, table, counts, tableT, countsT,
                block, scale, interpret):
    """Sparse backward: dq over the forward table, dk/dv over the transposed
    table. delta = rowsum(dO ∘ O) (the flash-backward correction term) is a
    cheap elementwise pass left to XLA."""
    B, H, S, D = q.shape
    BH = B * H
    nb = S // block
    qf, kf, vf = (t.reshape(BH, S, D) for t in (q, k, v))
    dof = g.reshape(BH, S, D)
    # [BH, S, 1]: row-wise scalars ride a trailing singleton so their block
    # spec's last two dims (block, 1) are Mosaic-legal
    delta = (dof.astype(jnp.float32)
             * o.reshape(BH, S, D).astype(jnp.float32)).sum(-1, keepdims=True)

    nheads_layout = table.shape[0]
    q_at = lambda b, i, ai, tbl, cnt: (b, i, 0)
    row_at = q_at
    tbl_at = lambda b, i, ai, tbl, cnt: (
        b, tbl[jax.lax.rem(b, tbl.shape[0]), i, ai], 0)
    tbl_row_at = tbl_at

    # ---- dq: grid (BH, q_block, active-k) ----
    A = table.shape[-1]
    dq = pl.pallas_call(
        functools.partial(_splash_dq_kernel, scale=scale, num_active=A,
                          nheads_layout=nheads_layout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb, A),
            in_specs=[
                pl.BlockSpec((1, block, D), q_at),      # q
                pl.BlockSpec((1, block, D), tbl_at),    # k
                pl.BlockSpec((1, block, D), tbl_at),    # v
                pl.BlockSpec((1, block, D), q_at),      # do
                pl.BlockSpec((1, block, 1), row_at),    # lse
                pl.BlockSpec((1, block, 1), row_at),    # delta
            ],
            out_specs=pl.BlockSpec((1, block, D), q_at),
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray(table), jnp.asarray(counts), qf, kf, vf, dof, lse, delta)

    # ---- dk/dv: grid (BH, k_block, active-q), transposed table ----
    At = tableT.shape[-1]
    dk, dv = pl.pallas_call(
        functools.partial(_splash_dkv_kernel, scale=scale, num_active=At,
                          nheads_layout=nheads_layout),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BH, nb, At),
            in_specs=[
                pl.BlockSpec((1, block, D), tbl_at),    # q (active q-block)
                pl.BlockSpec((1, block, D), q_at),      # k (this k-block)
                pl.BlockSpec((1, block, D), q_at),      # v
                pl.BlockSpec((1, block, D), tbl_at),    # do
                pl.BlockSpec((1, block, 1), tbl_row_at),  # lse (per q row)
                pl.BlockSpec((1, block, 1), tbl_row_at),  # delta
            ],
            out_specs=[pl.BlockSpec((1, block, D), q_at),
                       pl.BlockSpec((1, block, D), q_at)],
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32),
                            pltpu.VMEM((block, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), v.dtype)],
        interpret=interpret,
    )(jnp.asarray(tableT), jnp.asarray(countsT), qf, kf, vf, dof, lse, delta)

    return (dq.reshape(B, H, S, D), dk.reshape(B, H, S, D),
            dv.reshape(B, H, S, D))


@functools.lru_cache(maxsize=64)
def _cached_splash_fn(layout_bytes: bytes, layout_shape, block: int,
                      scale: float, interpret: bool):
    """The block table build (host Python loop) and the custom_vjp closure
    are cached per (layout, block, scale) — eager serving loops must not
    rebuild them every call."""
    layout = np.frombuffer(layout_bytes, dtype=np.bool_).reshape(layout_shape)
    table, counts = build_block_table(layout)
    # transposed layout: which q-blocks touch each k-block (dk/dv sweep)
    tableT, countsT = build_block_table(layout.transpose(0, 2, 1))

    @jax.custom_vjp
    def _f(q, k, v):
        return _splash_fwd(q, k, v, table, counts, block, scale, interpret)

    def _f_fwd(q, k, v):
        o, lse = _splash_fwd(q, k, v, table, counts, block, scale, interpret,
                             with_lse=True)
        return o, (q, k, v, o, lse)

    def _f_bwd(res, g):
        q, k, v, o, lse = res
        return _splash_bwd(q, k, v, o, lse, g, table, counts, tableT, countsT,
                           block, scale, interpret)

    _f.defvjp(_f_fwd, _f_bwd)
    return _f


def splash_sparse_attention(q, k, v, layout: np.ndarray, block: int,
                            scale: Optional[float] = None,
                            interpret: bool = False):
    """Block-sparse attention via the splash kernel; differentiable through
    sparse Pallas backward kernels (dq over the forward block table, dk/dv
    over the transposed table).

    q,k,v: [batch, heads, seq, head_dim]; layout: [heads or 1, nb, nb]
    static (a 1-head layout broadcasts over heads, dense-path parity).
    """
    B, H, S, D = q.shape
    lay = np.ascontiguousarray(np.asarray(layout).astype(bool))
    if S % block != 0:
        raise ValueError(f"seq {S} not divisible by block {block}")
    if H % lay.shape[0] != 0:
        raise ValueError(f"q heads {H} not a multiple of layout heads {lay.shape[0]}")
    scale = scale if scale is not None else 1.0 / float(np.sqrt(D))
    f = _cached_splash_fn(lay.tobytes(), lay.shape, int(block), float(scale),
                          bool(interpret))
    return f(q, k, v)


def splash_flops(layout: np.ndarray, block: int, head_dim: int,
                 batch: int = 1) -> dict:
    """Analytic fwd FLOP accounting: the kernel's work is structurally
    proportional to ACTIVE blocks (grid × per-tile matmuls), vs nb² for the
    dense-mask path — the reduction the reference's Triton SDD/DSD delivers."""
    layout = np.asarray(layout).astype(bool)
    H, nb, _ = layout.shape
    active = int(layout.sum())
    per_block = 4 * block * block * head_dim  # QK^T + PV
    return {
        "active_blocks": active,
        "total_blocks": H * nb * nb,
        "sparse_flops": batch * active * per_block,
        "dense_flops": batch * H * nb * nb * per_block,
        "reduction": 1.0 - active / (H * nb * nb),
    }
