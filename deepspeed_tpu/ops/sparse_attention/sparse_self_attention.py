"""Block-sparse self attention.

Reference: ``deepspeed/ops/sparse_attention/sparse_self_attention.py:12
SparseSelfAttention`` + the Triton ``matmul.py``/``softmax.py`` block
kernels. TPU path: the Pallas splash kernel (``splash.py``) consumes the
block layout as a scalar-prefetched block table and SKIPS masked tiles —
compute ∝ active blocks, matching the Triton SDD/DSD capability; the
masked dense einsum here is the fallback (padding masks, odd shapes) and
the numerics oracle.
"""

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..registry import registry
from .sparsity_config import SparsityConfig, FixedSparsityConfig


def layout_to_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """[heads, nb, nb] block layout → [heads, seq, seq] boolean mask."""
    return np.kron(layout, np.ones((block, block), dtype=np.int64)).astype(bool)


def sparse_attention(q, k, v, layout: np.ndarray, block: int,
                     key_padding_mask: Optional[jnp.ndarray] = None,
                     scale: Optional[float] = None,
                     key_padding_mask_mode: str = "mul",
                     use_kernel: Optional[bool] = None):
    """Masked attention under a block-sparse layout.
    q,k,v: [batch, heads, seq, head_dim]; layout: [heads, nb, nb].
    key_padding_mask [b, s]: mode 'mul' = keep-mask (True/1 = attend);
    mode 'add' = additive float mask (0 = keep, large-negative = drop) —
    the reference's two conventions (sparse_self_attention.py:12).

    On TPU (no padding mask) this dispatches to the Pallas splash kernel
    (splash.py), whose compute scales with ACTIVE blocks; the dense masked
    einsum is the fallback/oracle. use_kernel forces either path."""
    b, h, s, d = q.shape
    scale = scale if scale is not None else (1.0 / float(np.sqrt(d)))
    if use_kernel and key_padding_mask is not None:
        raise ValueError("the splash kernel does not take key_padding_mask; "
                         "fold padding into the layout or use the dense path")
    if use_kernel is None:
        from ..registry import on_tpu
        use_kernel = (key_padding_mask is None and s % block == 0
                      and on_tpu())
    if use_kernel:
        from .splash import splash_sparse_attention
        return splash_sparse_attention(q, k, v, layout, block, scale=scale)
    visible = jnp.asarray(layout_to_mask(layout, block))[None]  # [1, h, s, s]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    neg = jnp.finfo(jnp.float32).min
    if key_padding_mask is not None:
        kpm = key_padding_mask[:, None, None, :]
        if key_padding_mask_mode == "add" and kpm.dtype != jnp.bool_:
            # purely additive (reference semantics): moderate biases (e.g.
            # ALiBi-style values ≤ -1) must bias, not hard-mask — only the
            # sparse layout decides visibility here
            scores = scores + kpm.astype(jnp.float32)
        else:  # keep-mask (bool is always keep-style, whatever the mode)
            visible = visible & kpm.astype(bool)
    scores = jnp.where(visible, scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no visible key at all would softmax to uniform; zero them
    any_visible = visible.any(-1, keepdims=True)
    probs = jnp.where(any_visible, probs, 0.0).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class SparseSelfAttention:
    """Reference-parity wrapper: config-held layout, __call__(q, k, v)."""

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layout_cache = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None):
        s = query.shape[2]
        layout = self.get_layout(s)
        return sparse_attention(query, key, value, layout,
                                self.sparsity_config.block, key_padding_mask,
                                key_padding_mask_mode=self.key_padding_mask_mode)


try:
    from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    _SPARSE_BACKEND = "pallas"
except ImportError:  # pragma: no cover
    _SPARSE_BACKEND = "xla"
registry.register("sparse_attention", _SPARSE_BACKEND, True,
                  "splash block-sparse kernel, sparse fwd AND bwd (dq via "
                  "forward block table, dk/dv via transposed table); "
                  "masked-dense XLA fallback via use_kernel=False")
