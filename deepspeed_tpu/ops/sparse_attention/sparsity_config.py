"""Block-sparse attention layouts.

Reference: ``deepspeed/ops/sparse_attention/sparsity_config.py`` — config
classes emitting a block-level layout tensor [heads, nblocks, nblocks]
(1 = compute this q-block × k-block tile). Same pattern vocabulary (fixed
windows + periodic global, BigBird window+global+random, Longformer sliding
window + designated global blocks, per-head variable); the layout math is
host-side numpy, consumed on device as a mask (dense fallback) or a Pallas
block map (splash-kernel upgrade path).
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base (reference sparsity_config.py SparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} must be divisible by block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=np.int64)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _apply_causal(self, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        return layout * np.tril(np.ones((nb, nb), dtype=np.int64))


class DenseSparsityConfig(SparsityConfig):
    """All blocks on (reference DenseSparsityConfig)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks (reference
    FixedSparsityConfig; Sparse Transformer-style)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        L = self.num_local_blocks
        G = self.num_global_blocks
        for h in range(self.num_heads):
            # which G blocks of each window act as global, rotated per head
            pattern = (h % self.num_different_global_patterns
                       if self.different_layout_per_head else 0)
            for i in range(nb):
                w = i // L
                layout[h, i, w * L:min((w + 1) * L, nb)] = 1  # local window
                # the global blocks of every window up to and including ours
                for ww in range(w + 1):
                    g_end = min((ww + 1) * L - pattern * G, nb)
                    g_start = max(g_end - G, ww * L)
                    if g_start < g_end:
                        layout[h, i, g_start:g_end] = 1
            if self.horizontal_global_attention:  # global blocks also attend to all
                for ww in range((nb + L - 1) // L):
                    g_end = min((ww + 1) * L - pattern * G, nb)
                    g_start = max(g_end - G, ww * L)
                    if g_start < g_end:
                        layout[h, g_start:g_end, :] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global (reference BigBirdSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_heads):
            r = rng if self.different_layout_per_head else np.random.default_rng(self.seed)
            # sliding window
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = 1
            # global rows+cols
            g = min(self.num_global_blocks, nb)
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
            # random blocks per row
            for i in range(nb):
                cols = r.choice(nb, size=min(self.num_random_blocks, nb), replace=False)
                layout[h, i, cols] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global blocks (reference
    BSLongformerSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for i in range(nb):
            layout[:, i, max(0, i - w):min(nb, i + w + 1)] = 1
        if self.global_block_end_indices:
            spans = zip(self.global_block_indices, self.global_block_end_indices)
        else:
            spans = ((i, i + 1) for i in self.global_block_indices)
        for start, end in spans:
            start, end = min(start, nb), min(end, nb)
            layout[:, start:end, :] = 1
            layout[:, :, start:end] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Per-row-group variable windows + global + random (reference
    VariableSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        # consecutive local windows of varying size; last size repeats
        start = 0
        widx = 0
        while start < nb:
            size = self.local_window_blocks[min(widx, len(self.local_window_blocks) - 1)]
            end = min(start + size, nb)
            layout[:, start:end, start:end] = 1
            start = end
            widx += 1
        if self.global_block_end_indices:
            spans = zip(self.global_block_indices, self.global_block_end_indices)
        else:
            spans = ((i, i + 1) for i in self.global_block_indices)
        for s, e in spans:
            s, e = min(s, nb), min(e, nb)
            layout[:, s:e, :] = 1
            layout[:, :, s:e] = 1
        if self.num_random_blocks:
            shared = np.random.default_rng(self.seed)
            for h in range(self.num_heads):
                # identical layout per head unless different_layout_per_head
                # (same contract as BigBirdSparsityConfig)
                rng = shared if self.different_layout_per_head \
                    else np.random.default_rng(self.seed)
                for i in range(nb):
                    cols = rng.choice(nb, size=min(self.num_random_blocks, nb),
                                      replace=False)
                    layout[h, i, cols] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout
