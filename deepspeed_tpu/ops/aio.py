"""Async NVMe IO — ctypes binding over the C++ thread-pool library.

Reference: ``op_builder/async_io.py`` (AsyncIOBuilder, jit_load) +
``csrc/aio/py_lib``. The builder compiles ``csrc/aio/ds_aio.cpp`` with g++ at
first use into a cached shared object (the jit_load analog —
``op_builder/builder.py:535``), binds it via ctypes (no pybind11 in the
image), and falls back to a pure-Python thread pool when no toolchain is
available, mirroring the reference's compatibility-probe behavior
(``async_io.py is_compatible``).
"""

import ctypes
import os
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np

from ..utils.logging import logger
from .jit_build import jit_build
from .registry import registry

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "csrc", "aio", "ds_aio.cpp")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _jit_load() -> Optional[ctypes.CDLL]:
    """Compile-if-stale then dlopen (reference builder.py:535 jit_load)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:  # lock-free fast path for hot callers
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            so_path = jit_build(_SRC, "libds_aio", ["-pthread"])
            lib = ctypes.CDLL(so_path)
            lib.ds_aio_handle_new.restype = ctypes.c_void_p
            lib.ds_aio_handle_new.argtypes = [ctypes.c_int, ctypes.c_long, ctypes.c_int]
            lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
            for fn in (lib.ds_aio_submit_read, lib.ds_aio_submit_write):
                fn.restype = ctypes.c_long
                fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                               ctypes.c_long, ctypes.c_long]
            lib.ds_aio_wait.restype = ctypes.c_long
            lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.ds_aio_wait_all.restype = ctypes.c_long
            lib.ds_aio_wait_all.argtypes = [ctypes.c_void_p]
            _lib = lib
            registry.register("aio", "native", True)
        except (subprocess.CalledProcessError, OSError) as e:
            logger.warning(f"ds_aio native build unavailable ({e}); using thread-pool fallback")
            _build_failed = True
            registry.register("aio", "fallback", True)
        return _lib


def aio_available() -> bool:
    """True when the native library is usable (ds_report probe)."""
    return _jit_load() is not None


def aligned_empty(nbytes: int, align: int = 4096) -> np.ndarray:
    """Uninitialized uint8 buffer whose data pointer is `align`-aligned
    (reference csrc/aio pins page-aligned bounce buffers for O_DIRECT).
    A 4096-aligned destination lets the native lib pread STRAIGHT into it
    under O_DIRECT instead of bouncing+memcpying every block. The returned
    array is a view into a slightly larger allocation; its ``.base`` keeps
    the backing alive, so ownership transfers (e.g. to jax.device_put)
    work as with a plain np.empty."""
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes]


class AsyncIOHandle:
    """Submission handle (reference csrc/aio/py_lib/deepspeed_py_io_handle.cpp
    semantics: submit read/write of a host buffer, wait on completion).

    Buffers must be writable C-contiguous numpy arrays; they are pinned by
    keeping a reference until wait() — the caller must not resize them.
    """

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 thread_count: int = 4, use_o_direct: bool = False):
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self._inflight: Dict[int, np.ndarray] = {}
        self._lib = _jit_load()
        if self._lib is not None:
            self._h = self._lib.ds_aio_handle_new(thread_count, block_size,
                                                  1 if use_o_direct else 0)
            self._pool = None
        else:
            self._h = None
            self._pool = ThreadPoolExecutor(max_workers=thread_count)
            self._futures = {}
            self._next_id = 1

    # ---- fallback helpers ----

    def _py_read(self, path, buf, offset):
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(buf.nbytes)
        flat = buf.reshape(-1).view(np.uint8)
        flat[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        return len(data)

    def _py_write(self, path, buf, offset):
        mode = "r+b" if os.path.exists(path) else "wb"
        with open(path, mode) as f:
            f.seek(offset)
            f.write(buf.tobytes())
        return buf.nbytes

    # ---- public API ----

    def submit_read(self, path: str, buffer: np.ndarray, offset: int = 0) -> int:
        assert buffer.flags["C_CONTIGUOUS"] and buffer.flags["WRITEABLE"]
        if self._h is not None:
            rid = self._lib.ds_aio_submit_read(
                self._h, path.encode(), buffer.ctypes.data_as(ctypes.c_void_p),
                buffer.nbytes, offset)
        else:
            rid = self._next_id
            self._next_id += 1
            self._futures[rid] = self._pool.submit(self._py_read, path, buffer, offset)
        self._inflight[rid] = buffer
        return rid

    def submit_write(self, path: str, buffer: np.ndarray, offset: int = 0) -> int:
        assert buffer.flags["C_CONTIGUOUS"]
        if self._h is not None:
            rid = self._lib.ds_aio_submit_write(
                self._h, path.encode(), buffer.ctypes.data_as(ctypes.c_void_p),
                buffer.nbytes, offset)
        else:
            rid = self._next_id
            self._next_id += 1
            self._futures[rid] = self._pool.submit(self._py_write, path, buffer, offset)
        self._inflight[rid] = buffer
        return rid

    def wait(self, request_id: int) -> int:
        """Bytes transferred; raises OSError on IO failure."""
        if self._h is not None:
            rc = self._lib.ds_aio_wait(self._h, request_id)
        else:
            rc = self._futures.pop(request_id).result()
        self._inflight.pop(request_id, None)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return rc

    def wait_all(self) -> None:
        if self._h is not None:
            rc = self._lib.ds_aio_wait_all(self._h)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc))
        else:
            for rid in list(self._futures):
                self.wait(rid)
        self._inflight.clear()

    # sync conveniences (reference sync_pread/sync_pwrite)
    def pread(self, path: str, buffer: np.ndarray, offset: int = 0) -> int:
        return self.wait(self.submit_read(path, buffer, offset))

    def pread_striped(self, path: str, buffer: np.ndarray, offset: int = 0,
                      stripes: Optional[int] = None) -> int:
        """Parallel pread: split the range into `stripes` aligned sub-ranges
        (default: one per pool thread) and fan them out. One Request is
        executed serially by ONE worker (reference deepspeed_aio_thread.cpp
        semantics), so a single big pread leaves thread_count-1 workers
        idle — striping is what actually engages the pool for bulk loads."""
        # assert on the CALLER's buffer: reshape(-1) of a non-contiguous view
        # would copy, the stripes would land in the copy, and the caller's
        # buffer would silently hold garbage
        assert buffer.flags["C_CONTIGUOUS"] and buffer.flags["WRITEABLE"]
        n = int(buffer.nbytes)
        k = max(1, min(stripes or self.thread_count, n // (1 << 20) or 1))
        if k == 1:
            return self.pread(path, buffer, offset)
        # stripe boundaries stay 4096-multiples so O_DIRECT offsets (and
        # aligned-destination preads) hold on every stripe
        per = -(-n // k)
        per += (-per) % 4096
        flat = buffer.reshape(-1).view(np.uint8)
        rids = []
        for s in range(0, n, per):
            e = min(s + per, n)
            rids.append(self.submit_read(path, flat[s:e], offset + s))
        total = 0
        err = None
        for rid in rids:
            try:
                total += self.wait(rid)
            except OSError as ex:  # drain every stripe before raising
                err = err or ex
        if err is not None:
            raise err
        return total

    def pwrite(self, path: str, buffer: np.ndarray, offset: int = 0) -> int:
        return self.wait(self.submit_write(path, buffer, offset))

    def close(self):
        if self._h is not None:
            self._lib.ds_aio_handle_free(self._h)
            self._h = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
