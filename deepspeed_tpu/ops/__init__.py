"""TPU-native op layer.

Rebuild of reference ``deepspeed/ops`` + ``op_builder/``: instead of JIT-built
CUDA extensions, each op is a pure function that dispatches to a Pallas TPU
kernel when running on TPU and to an equivalent XLA (jnp) implementation
elsewhere (CPU tests, interpret mode). The registry mirrors ``op_builder``'s
compatibility reporting (``ds_report``).
"""

from .registry import OpRegistry, compatible_ops, op_report, registry
from .attention import flash_attention
from .normalization import rms_norm, layer_norm
from .rope import apply_rotary_pos_emb
from .quantizer import quantize_int8_blockwise, dequantize_int8_blockwise
from .fused_optimizer import fused_adam_step, fused_lamb_step, fused_lion_step
from .evoformer_attn import DS4Sci_EvoformerAttention, evoformer_attention

__all__ = [
    "OpRegistry", "registry", "compatible_ops", "op_report",
    "flash_attention", "rms_norm", "layer_norm", "apply_rotary_pos_emb",
    "quantize_int8_blockwise", "dequantize_int8_blockwise", "fused_adam_step",
    "fused_lion_step", "fused_lamb_step", "evoformer_attention",
    "DS4Sci_EvoformerAttention",
]
