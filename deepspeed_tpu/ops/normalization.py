"""RMSNorm / LayerNorm — Pallas kernels with XLA fallback.

TPU-native equivalents of reference ``csrc/transformer/inference/csrc/
{rms_norm.cu, layer_norm.cu}`` (fused residual-add variants included). The
row reduction + scale fits one VMEM block per row tile; XLA fuses the
fallback fine, so the kernels mostly matter as fusion anchors for larger
Pallas pipelines.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps) * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _row_call(kernel, x, weights, block_rows=256, interpret=False):
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    br = min(block_rows, n)
    pad = (-n) % br
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    rows = x2.shape[0]
    out = pl.pallas_call(
        kernel,
        grid=(rows // br, ),
        in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0))] +
        [pl.BlockSpec((d, ), lambda i: (0, )) for _ in weights],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, *weights)
    if pad:
        out = out[:n]
    return out.reshape(orig_shape)


def rms_norm(x, weight, eps: float = 1e-6, force_pallas: Optional[bool] = None,
             interpret: bool = False):
    """y = x / rms(x) * weight (reference rms_norm.cu)."""
    if use_pallas(force_pallas) or interpret:
        return _row_call(functools.partial(_rms_kernel, eps=eps), x, (weight, ),
                         interpret=interpret)
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5, force_pallas: Optional[bool] = None,
               interpret: bool = False):
    """Standard layernorm (reference layer_norm.cu)."""
    if use_pallas(force_pallas) or interpret:
        return _row_call(functools.partial(_ln_kernel, eps=eps), x, (weight, bias),
                         interpret=interpret)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


registry.register("rms_norm", "pallas" if _HAS_PLTPU else "xla", True)
registry.register("layer_norm", "pallas" if _HAS_PLTPU else "xla", True)
