"""On-device token sampling for the v2 serving engine.

The numpy sampler (``engine_v2.InferenceEngineV2._sample_with_logprob`` /
``process_logits``) costs one host round-trip per generated token — on a
relay-attached TPU that is ~100ms+ of pure dispatch latency per token, so
any request with temperature/top-k/top-p/logprobs/repetition-penalty was
excluded from the fused K-step decode path. This module is the same
sampler expressed as jit-friendly jax ops, batched over the ragged row
layout [S, vocab], so sampling runs inside the fused ``lax.scan`` decode
program (and, for per-token ticks, as one batched dispatch per tick).

Semantics mirror the numpy oracle EXACTLY (the oracle stays in engine_v2
as the parity reference and the fallback for host-only
``logits_processor`` callbacks):

- ``temperature <= 0``: greedy over the RAW logits; logprob from the raw
  softmax.
- ``top_k``: kth-largest VALUE threshold (``np.partition`` semantics —
  ties at the kth value survive); ``top_k <= 0`` or ``>= vocab`` disables.
- ``top_p``: nucleus over the temperature-scaled, top-k-filtered logits;
  ``cumsum(p) - p < top_p`` keep rule (the argmax always survives);
  ``top_p <= 0`` degenerates to greedy over the filtered logits;
  ``top_p >= 1`` disables.
- sampling is Gumbel-max: ``argmax(logits + G)`` — filtered ``-inf``
  entries can never win.
- the selected-token logprob is computed on the FILTERED (renormalized)
  distribution, like the oracle's ``lp_at``.
- repetition penalty is the CTRL rule over the history SET (divide
  positive logits by p, multiply negative ones), applied before
  temperature — history arrives as a boolean presence mask [S, vocab] so
  the in-scan update is one scatter per step.
- eos masking (``min_new_tokens``) sets the eos column to ``-inf`` before
  sampling, per row.

Per-sequence determinism: each row carries its own ``jax.random`` key and
every sample performs ``key, sub = split(key)`` then draws with ``sub`` —
the threefry stream is a pure function of the initial key, so the
per-token path and the fused K-step path produce bit-identical token
streams under the same seed (the parity contract the scheduler relies on
when it moves a request between paths).
"""

import functools

import jax
import jax.numpy as jnp

from .registry import registry

_NEG_INF = float("-inf")


def apply_repetition_penalty(logits, seen_mask, penalties):
    """CTRL repetition penalty, batched: where ``seen_mask`` is True,
    positive logits divide by the row's penalty and negative ones multiply
    (``process_logits`` parity). ``penalties == 1`` rows pass through
    unchanged by construction. logits [S, V] f32, seen_mask [S, V] bool,
    penalties [S] f32."""
    p = penalties[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    return jnp.where(seen_mask, penalized, logits)


def mask_eos(logits, eos_ids, block):
    """Set the eos column to -inf per row where ``block`` is True
    (min_new_tokens gating). ``eos_ids`` [S] int32 (< 0 = no eos id);
    block [S] bool."""
    cols = jnp.arange(logits.shape[-1], dtype=jnp.int32)[None, :]
    hit = (cols == eos_ids[:, None]) & block[:, None] & (eos_ids >= 0)[:, None]
    return jnp.where(hit, _NEG_INF, logits)


def filter_top_k(logits, top_ks):
    """kth-largest VALUE threshold per row (oracle ``np.partition``
    semantics: ties at the kth value are kept). ``top_ks`` [S] int32;
    ``<= 0`` or ``>= vocab`` disables the row's filter."""
    V = logits.shape[-1]
    srt = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
    kk = jnp.clip(top_ks, 1, V)
    kth = jnp.take_along_axis(srt, (kk - 1)[:, None], axis=-1)  # [S, 1]
    on = ((top_ks > 0) & (top_ks < V))[:, None]
    return jnp.where(on & (logits < kth), _NEG_INF, logits)


def filter_top_p(logits, top_ps):
    """Nucleus filter per row: keep the smallest set of tokens whose
    softmax mass reaches ``top_p`` (``cumsum(p) - p < top_p`` — the
    highest-prob token always survives). Mirrors the oracle's tie order
    exactly: stable ascending argsort, reversed. ``top_ps`` [S] f32;
    rows with ``top_p <= 0`` or ``>= 1`` pass through (the degenerate
    ``top_p <= 0`` greedy case is the caller's branch, as in the
    oracle)."""
    S, V = logits.shape
    order = jnp.argsort(logits, axis=-1)[:, ::-1]  # oracle: argsort()[::-1]
    srt = jnp.take_along_axis(logits, order, axis=-1)
    p = jnp.exp(srt - srt[:, :1])  # srt[:,0] is the row max
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    keep_sorted = (jnp.cumsum(p, axis=-1) - p) < top_ps[:, None]
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    keep = jnp.zeros((S, V), bool).at[rows, order].set(keep_sorted)
    on = ((top_ps > 0.0) & (top_ps < 1.0))[:, None]
    return jnp.where(on & ~keep, _NEG_INF, logits)


def selected_logprob(logits, toks):
    """log p(tok) under softmax(logits), per row — correct on filtered
    (-inf) logits: ``exp(-inf - m)`` is 0, so the mass renormalizes over
    the surviving set (oracle ``lp_at``)."""
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(logits, toks[:, None], axis=-1)[:, 0]
    return picked - lse


def sample_core(logits, keys, temps, top_ks, top_ps, *, want_logprobs):
    """One sampling step over a batch of rows — the shared core of the
    per-token dispatch and the fused decode scan.

    logits [S, V] (any float dtype; promoted to f32), keys [S, 2] uint32
    (one legacy PRNG key per row), temps/top_ps [S] f32, top_ks [S] int32.
    Returns ``(toks [S] int32, logprobs [S] f32, new_keys [S, 2])`` —
    logprobs are zeros when ``want_logprobs`` is False (statically skips
    the extra logsumexp). Every row advances its key by exactly one
    ``split`` whether it samples or not — key-chain parity between paths
    does not depend on which rows happened to be greedy."""
    raw = logits.astype(jnp.float32)
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)  # [S, 2, 2]
    new_keys, subs = split[:, 0], split[:, 1]

    temps_safe = jnp.where(temps > 0, temps, 1.0)
    scaled = raw / temps_safe[:, None]
    filt = filter_top_p(filter_top_k(scaled, top_ks), top_ps)

    g = jax.vmap(
        lambda k: jax.random.gumbel(k, (raw.shape[-1],), jnp.float32))(subs)
    tok_sampled = jnp.argmax(filt + g, axis=-1).astype(jnp.int32)
    tok_greedy = jnp.argmax(raw, axis=-1).astype(jnp.int32)
    # oracle: top_p <= 0 is degenerate nucleus = greedy over the
    # scaled+top-k-filtered logits
    tok_degenerate = jnp.argmax(filt, axis=-1).astype(jnp.int32)

    greedy = temps <= 0
    degenerate = (~greedy) & (top_ps <= 0.0)
    toks = jnp.where(greedy, tok_greedy,
                     jnp.where(degenerate, tok_degenerate, tok_sampled))
    if want_logprobs:
        lp_src = jnp.where(greedy[:, None], raw, filt)
        lps = selected_logprob(lp_src, toks)
    else:
        lps = jnp.zeros(raw.shape[0], jnp.float32)
    return toks, lps, new_keys


def apply_logit_controls(logits, *, seen_mask=None, penalties=None,
                         eos_ids=None, block_eos=None):
    """Pre-sampling logit controls (``process_logits`` parity): repetition
    penalty over the history presence mask, then eos masking. Pass None to
    statically skip a control."""
    logits = logits.astype(jnp.float32)
    if seen_mask is not None:
        logits = apply_repetition_penalty(logits, seen_mask, penalties)
    if block_eos is not None:
        logits = mask_eos(logits, eos_ids, block_eos)
    return logits


@functools.partial(jax.jit, static_argnames=("want_logprobs", "use_penalty",
                                             "use_eos_mask"))
def sample_step(logits, keys, temps, top_ks, top_ps, seen_mask, penalties,
                eos_ids, block_eos, *, want_logprobs, use_penalty,
                use_eos_mask):
    """Jitted controls + sample for one batched per-token dispatch. Unused
    control operands may be passed as None (they are statically elided by
    the flags, which are part of the compile key)."""
    ctrl = apply_logit_controls(
        logits,
        seen_mask=seen_mask if use_penalty else None,
        penalties=penalties if use_penalty else None,
        eos_ids=eos_ids if use_eos_mask else None,
        block_eos=block_eos if use_eos_mask else None)
    return sample_core(ctrl, keys, temps, top_ks, top_ps,
                       want_logprobs=want_logprobs)


# ---------------------------------------------------------------------------
# Speculative decoding: on-device drafting + verification
#
# Prompt-lookup drafts are POINT MASSES (the draft "distribution" puts all
# its mass on the looked-up token), so standard speculative rejection
# sampling collapses to a target-probability coin flip: accept draft t with
# probability min(1, p_target(t) / q(t)) = p_target(t), and on the first
# rejection sample from the residual norm(max(0, p - q)) — which for a
# point mass is just p with the rejected token zeroed and renormalized.
# Greedy rows (temperature <= 0) verify by exact argmax match, reproducing
# the host ``accept_drafts`` byte-for-byte.
#
# Key discipline: each row advances its chain by exactly ONE ``split`` per
# verified window (not per token), and derives the window's d coin flips +
# one correction/bonus draw from the consumed sub-key via a fixed
# ``split(sub, d + 1)`` — the draw count is independent of the accept
# pattern and of batch composition, so the fused program and the host
# fallback (which calls the same functions row-at-a-time) produce
# bit-identical streams from the same starting key.
# ---------------------------------------------------------------------------


def ngram_draft_ring(hist, hist_len, ngrams, max_drafts, *, max_ngram, d):
    """Vectorized prompt-lookup drafting over per-row token-history ring
    buffers — the device-side ``prompt_lookup_draft``.

    ``hist`` [S, W] int32 holds the trailing W tokens of each row's
    prompt+output history with token at logical position p stored at
    ``p % W``; ``hist_len`` [S] is the logical history length. ``ngrams``
    and ``max_drafts`` are per-row (dynamic) so one compiled program
    serves mixed requests; ``max_ngram`` and ``d`` (draft width) are
    static. Returns ``(drafts [S, d] int32, dlen [S] int32)`` where
    ``dlen`` is how many leading draft entries are real (0 = no match —
    the row decodes one token this window like a plain decode).

    Match semantics mirror the host scan: find the MOST RECENT earlier
    occurrence of the trailing ``ngram`` tokens (excluding the trivial
    self-match) and draft the tokens that followed it, capped by
    ``max_drafts`` and by how many tokens actually follow the match."""
    S, W = hist.shape
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]          # s_off: 0 = most recent
    # candidate match start (logical position): s = len - ngram - 1 - s_off
    s = hist_len[:, None] - ngrams[:, None] - 1 - offs       # [S, W]
    oldest = jnp.maximum(0, hist_len - W)                    # oldest retained pos
    valid = s >= oldest[:, None]
    jj = jnp.arange(max_ngram, dtype=jnp.int32)
    pat_pos = hist_len[:, None] - ngrams[:, None] + jj[None, :]      # [S, G]
    pat = hist[rows, pat_pos % W]                                    # [S, G]
    cand_pos = s[:, :, None] + jj[None, None, :]                     # [S, W, G]
    cand = hist[rows[:, :, None], cand_pos % W]                      # [S, W, G]
    eq = (cand == pat[:, None, :]) | (jj[None, None, :] >= ngrams[:, None, None])
    ok_row = (hist_len > ngrams) & (max_drafts > 0) & (ngrams > 0)
    match = valid & jnp.all(eq, axis=-1) & ok_row[:, None]           # [S, W]
    any_match = jnp.any(match, axis=1)
    s_off = jnp.argmax(match, axis=1).astype(jnp.int32)      # first True = most recent
    # draft tokens follow the match: logical positions (s + ngram) + j,
    # of which exactly s_off + 1 precede the end of history
    start = hist_len - 1 - s_off
    dpos = start[:, None] + jnp.arange(d, dtype=jnp.int32)[None, :]
    drafts = hist[rows, dpos % W]                                    # [S, d]
    dlen = jnp.where(any_match, jnp.minimum(max_drafts, s_off + 1), 0)
    return drafts, dlen.astype(jnp.int32)


def spec_verify_window(window_logits, drafts, dlen, keys, temps, top_ks,
                       top_ps, *, d):
    """Verify one speculative window on device and emit the accepted
    tokens plus the correction/bonus token.

    ``window_logits`` [S, 1+d, V] are the target model's next-token logits
    at the fed positions (position j conditions on the input token and
    drafts[:j]); ``drafts`` [S, d] with ``dlen`` [S] real entries; keys
    [S, 2]; temps/top_ks/top_ps as in ``sample_core``. Static ``d`` must
    match the window width.

    Returns ``(out [S, 1+d] int32, n_emit [S] int32, new_keys)``: row i
    emits ``out[i, :n_emit[i]]`` — its accepted drafts followed by one
    token sampled from the residual at the rejection position (or from
    the full distribution at position dlen when every draft was accepted
    — the "bonus" token). ``n_emit - 1`` is the accepted-draft count.
    Greedy rows use exact argmax verification and never consult the
    random draws (their streams are key-independent, like ``sample_core``)."""
    S, Np1, V = window_logits.shape
    raw = window_logits.astype(jnp.float32)
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    new_keys, subs = split[:, 0], split[:, 1]
    rsub = jax.vmap(lambda k: jax.random.split(k, d + 1))(subs)      # [S, d+1, 2]

    temps_safe = jnp.where(temps > 0, temps, 1.0)
    flat = raw.reshape(S * Np1, V)
    rep = lambda a: jnp.repeat(a, Np1, axis=0)
    scaled = flat / rep(temps_safe)[:, None]
    filt = filter_top_p(filter_top_k(scaled, rep(top_ks)),
                        rep(top_ps)).reshape(S, Np1, V)

    greedy = temps <= 0
    degenerate = (~greedy) & (top_ps <= 0.0)
    g_tok = jnp.argmax(raw, axis=-1).astype(jnp.int32)               # [S, 1+d]
    deg_tok = jnp.argmax(filt, axis=-1).astype(jnp.int32)

    # accept test per draft position: coin flip against the target prob of
    # the (point-mass) draft token under the filtered/scaled distribution
    lp_d = selected_logprob(filt[:, :d].reshape(S * d, V),
                            drafts.reshape(S * d)).reshape(S, d)
    u = jax.vmap(jax.vmap(lambda k: jax.random.uniform(k, ())))(rsub[:, :d])
    acc = jnp.where(greedy[:, None], drafts == g_tok[:, :d],
                    jnp.where(degenerate[:, None], drafts == deg_tok[:, :d],
                              u < jnp.exp(lp_d)))
    dj = jnp.arange(d, dtype=jnp.int32)[None, :]
    acc = acc & (dj < dlen[:, None])
    m = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                axis=1).astype(jnp.int32)                    # accepted prefix length

    # correction token from position m: residual (draft token zeroed) when
    # a draft was rejected there, the full distribution otherwise (bonus)
    rows = jnp.arange(S, dtype=jnp.int32)
    logit_m_raw = raw[rows, m]
    logit_m_filt = filt[rows, m]
    rejected = m < dlen
    rej_tok = drafts[rows, jnp.minimum(m, d - 1)]
    cols = jnp.arange(V, dtype=jnp.int32)[None, :]
    resid = jnp.where(rejected[:, None] & (cols == rej_tok[:, None]),
                      _NEG_INF, logit_m_filt)
    gum = jax.vmap(lambda k: jax.random.gumbel(k, (V,), jnp.float32))(
        rsub[:, d])
    corr = jnp.where(greedy, jnp.argmax(logit_m_raw, axis=-1),
                     jnp.where(degenerate, jnp.argmax(logit_m_filt, axis=-1),
                               jnp.argmax(resid + gum, axis=-1))).astype(jnp.int32)

    jfull = jnp.arange(Np1, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)   # [S, 1+d]
    out = jnp.where(jfull < m[:, None], drafts_pad, corr[:, None])
    return out, m + 1, new_keys


def ring_append(hist, hist_len, toks, n):
    """Append ``toks[i, :n[i]]`` to row i's history ring (same layout as
    ``ngram_draft_ring``): token for logical position p lands in slot
    ``p % W``; entries past ``n`` scatter out of bounds and drop. Requires
    the append width <= W so slots within one call are distinct."""
    S, W = hist.shape
    jj = jnp.arange(toks.shape[1], dtype=jnp.int32)[None, :]
    pos = hist_len[:, None] + jj
    idx = jnp.where(jj < n[:, None], pos % W, W)             # W = OOB -> dropped
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    return hist.at[rows, idx].set(toks, mode="drop"), hist_len + n


registry.register("sampling", "xla", True,
                  "on-device temperature/top-k/top-p sampling + logit "
                  "controls (fused-decode resident; numpy oracle retained "
                  "for logits_processor callbacks)")

registry.register("speculative", "xla", True,
                  "on-device prompt-lookup drafting (ring-buffer n-gram "
                  "match) + window verification / rejection sampling "
                  "(fused-decode resident; host prompt_lookup_draft + "
                  "accept_drafts retained as the per-token parity oracle)")
