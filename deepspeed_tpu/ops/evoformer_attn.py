"""Evoformer (DS4Science) fused attention — TPU-native.

Reference: ``deepspeed/ops/deepspeed4science/evoformer_attn.py`` (API:
``DS4Sci_EvoformerAttention(Q, K, V, [bias1, bias2])`` over ``[*, L, H, D]``
tensors, logit biases broadcast into ``[*, H, Lq, Lk]``) backed by the CUTLASS
kernels in ``csrc/deepspeed4science/evoformer_attn/``. The CUDA kernel's value
is avoiding the O(L^2) logits materialization for AlphaFold-scale MSA/pair
stacks; the TPU equivalent gets the same memory behavior from an
online-softmax scan over key blocks — each block's ``[*, H, Lq, block]``
logits live only inside one scan step, XLA fuses the bias add + exp into the
matmuls, and autodiff through the scan provides the backward (the reference
ships a hand-written ``attention_bwd``; here ``jax.checkpoint`` on the block
body gives the same recompute-not-store tradeoff).

Numerics: logits accumulate in fp32 (softmax_lse parity with the reference's
fp32 ``lse`` buffer); output is cast back to the query dtype.
"""

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .registry import registry


def _dense_attention(q, k, v, biases, scale):
    # operands stay in the input dtype (MXU bf16 fast path); fp32 comes
    # from the dot's accumulator (preferred_element_type), not a pre-cast
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    for b in biases:
        logits = logits + b.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("...hqk,...khd->...qhd", probs.astype(q.dtype), v)
    return out


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Sequence[jax.Array] = (),
                        block_size: Optional[int] = 512) -> jax.Array:
    """Attention over ``[*, L, H, D]`` with up to two broadcastable logit
    biases (mask bias ``[B, N, 1, 1, L]`` and pair bias ``[B, 1, H, L, L]`` in
    AlphaFold's layout — anything broadcastable to ``[*, H, Lq, Lk]`` works).

    ``block_size``: key-block width of the online-softmax scan. ``None`` (or
    ``>= Lk``) computes the dense form in one shot — right for short L where
    the logits fit HBM comfortably.
    """
    if len(biases) > 2:
        raise ValueError(f"evoformer_attention takes at most 2 biases, got {len(biases)}")
    Lk = k.shape[-3]
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)

    if block_size is None or block_size >= Lk:
        return _dense_attention(q, k, v, biases, scale)

    pad = (-Lk) % block_size
    if pad:
        # pad K/V to a block multiple with a -inf logit tail so the
        # online-softmax scan (the whole memory win) still applies at
        # AlphaFold-scale lengths that aren't block multiples — the dense
        # fallback here would materialize exactly the O(L^2) logits this op
        # exists to avoid
        kv_pad = [(0, 0)] * (k.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
        k = jnp.pad(k, kv_pad)
        v = jnp.pad(v, kv_pad)
        biases = tuple(
            jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, pad)])
            if b.shape[-1] == Lk else b for b in biases)
        tail_mask = jnp.where(jnp.arange(Lk + pad) < Lk, 0.0, -jnp.inf)
        biases = biases + (tail_mask.astype(jnp.float32), )
        Lk = Lk + pad

    nblocks = Lk // block_size
    # [*, H, Lq, Lk] biases, split along the key axis per scan step
    bcast = [jnp.broadcast_to(b, b.shape[:-2] + (q.shape[-3], Lk)) for b in biases]

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        kb, vb, bias_blk = blk
        # operands in input dtype (MXU bf16 fast path); the fp32 comes from
        # the dot accumulator, and scale applies to the fp32 logits
        logits = jnp.einsum("...qhd,...khd->...hqk", q, kb,
                            preferred_element_type=jnp.float32) * scale
        for b in bias_blk:
            logits = logits + b.astype(jnp.float32)
        m_cur = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("...hqk,...khd->...qhd", p.astype(vb.dtype), vb,
                        preferred_element_type=jnp.float32)
        # acc is [*, Lq, H, D]; corr is [*, H, Lq] -> move heads behind queries
        acc_new = acc * jnp.moveaxis(corr, -2, -1)[..., None] + pv
        return (m_new, l_new, acc_new), None

    kb = jnp.stack(jnp.split(k, nblocks, axis=-3))
    vb = jnp.stack(jnp.split(v, nblocks, axis=-3))
    bias_blocks = tuple(jnp.stack(jnp.split(b, nblocks, axis=-1)) for b in bcast)

    Hq, Lq = q.shape[-2], q.shape[-3]
    batch_shape = q.shape[:-3]
    m0 = jnp.full(batch_shape + (Hq, Lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros(batch_shape + (Hq, Lq), jnp.float32)
    acc0 = jnp.zeros(batch_shape + (Lq, Hq, d), jnp.float32)

    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(body), (m0, l0, acc0),
                                  (kb, vb, bias_blocks))
    out = acc / jnp.moveaxis(l, -2, -1)[..., None]
    return out.astype(q.dtype)


# reference alias (deepspeed/ops/deepspeed4science/evoformer_attn.py:110)
DS4Sci_EvoformerAttention = evoformer_attention

registry.register("evoformer_attn", "xla", True)
