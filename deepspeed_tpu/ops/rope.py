"""Rotary position embedding.

TPU-native equivalent of reference ``csrc/transformer/inference/csrc/
apply_rotary_pos_emb.cu`` and the v2 ``linear_blocked_kv_rotary`` fusion.
RoPE is pure elementwise (VPU work); XLA fuses it into the surrounding
matmuls, so the default path is jnp — the function exists as the op-layer
seam (and for parity with the reference op surface).
"""

from typing import Optional

import jax.numpy as jnp

from .registry import registry


def precompute_rope_freqs(head_dim: int, max_len: int, theta: float = 10000.0,
                          dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary_pos_emb(x, cos, sin, positions: Optional[jnp.ndarray] = None):
    """x: [B, S, H, D]; cos/sin: [max_len, D/2]; positions: [B, S] or [S]."""
    if positions is None:
        positions = jnp.arange(x.shape[1])
    c = cos[positions]  # [., S, D/2]
    s = sin[positions]
    if c.ndim == 2:
        c = c[None]
        s = s[None]
    c = c[:, :, None, :]
    s = s[:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1).astype(x.dtype)


registry.register("rotary_pos_emb", "xla", True, "elementwise; XLA-fused")
