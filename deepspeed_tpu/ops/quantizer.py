"""Blockwise int8 quantization — the ZeRO++ / compression workhorse.

TPU-native equivalent of reference ``csrc/quantization/`` (``quantize.cu``
symmetric block quant, ``swizzled_quantize.cu`` comm-layout variant,
``quant_reduce.cu`` fused dequant+reduce for qgZ): values are grouped into
fixed-size blocks, each block scaled by absmax/127 to int8.

Used by: qwZ (quantized weight allgather), qgZ (quantized gradient
all-to-all reduce), weight-only inference quantization, 1-bit optimizer wire
format. Pallas kernel for TPU; jnp fallback elsewhere (identical numerics).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas


def _quant_kernel(x_ref, v_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)  # [rows, block]
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    v_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _pad_to_blocks(flat, block_size):
    pad = (-flat.shape[0]) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad


def quantize_int8_blockwise(x, block_size: int = 2048,
                            force_pallas: Optional[bool] = None,
                            interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quant. Returns (values int8 [N], scales
    fp32 [N/block]); padding (zeros) is included in the trailing block."""
    orig = x.shape
    flat, _ = _pad_to_blocks(x.reshape(-1), block_size)
    rows = flat.shape[0] // block_size
    blocks = flat.reshape(rows, block_size)
    if use_pallas(force_pallas) or interpret:
        tile = min(rows, 256)
        pad_r = (-rows) % tile
        if pad_r:
            blocks = jnp.pad(blocks, ((0, pad_r), (0, 0)))
        v, s = pl.pallas_call(
            _quant_kernel,
            grid=(blocks.shape[0] // tile, ),
            in_specs=[pl.BlockSpec((tile, block_size), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec((tile, block_size), lambda i: (i, 0)),
                pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(blocks.shape, jnp.int8),
                jax.ShapeDtypeStruct((blocks.shape[0], 1), jnp.float32),
            ],
            interpret=interpret,
        )(blocks)
        if pad_r:
            v, s = v[:rows], s[:rows]
    else:
        xf = blocks.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        s = jnp.where(absmax == 0, 1.0, absmax / 127.0)
        v = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return v.reshape(-1), s.reshape(-1)


def dequantize_int8_blockwise(values, scales, shape, block_size: int = 2048,
                              dtype=jnp.float32):
    """Inverse of quantize_int8_blockwise (reference dequantize.cu)."""
    rows = values.shape[0] // block_size
    x = values.reshape(rows, block_size).astype(jnp.float32) * scales.reshape(rows, 1)
    n = 1
    for d in shape:
        n *= d
    return x.reshape(-1)[:n].reshape(shape).astype(dtype)


registry.register("quantizer_int8", "pallas" if _HAS_PLTPU else "xla", True)


# ---------------------------------------------------------------- FP8/FP quant

def quantize_fp8(x, dtype=jnp.float8_e4m3fn, block_size: int = 2048):
    """Blockwise-scaled FP8 quantization.

    Reference ``csrc/fp_quantizer/fp_quantize.cu`` (FP6-LLM-style low-bit
    float formats for weights). TPU-native version targets the hardware's
    fp8 dtypes (e4m3 for weights/activations, e5m2 for gradients); blocks
    are scaled so the absmax maps to the format's max normal, preserving
    dynamic range the way the reference's per-group scales do. For the
    6-bit tier see ``quantize_fp6_blockwise`` below (bit-packed e3m2
    storage, dequantized in-graph).

    Returns (values: dtype, scales: f32 per block).
    """
    finfo_max = float(jnp.finfo(dtype).max)
    flat = x.reshape(-1)
    padded, _ = _pad_to_blocks(flat, block_size)
    blocks = padded.reshape(-1, block_size).astype(jnp.float32)
    scales = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / finfo_max
    scales = jnp.maximum(scales, 1e-12)
    values = (blocks / scales).astype(dtype)
    return values, scales[:, 0]


def dequantize_fp8(values, scales, shape, block_size: int = 2048):
    """Inverse of quantize_fp8."""
    blocks = values.astype(jnp.float32) * scales[:, None]
    import numpy as _np
    n = int(_np.prod(shape))
    return blocks.reshape(-1)[:n].reshape(shape)


registry.register("fp_quantizer", "xla", True,
                  "fp8 e4m3/e5m2 native dtypes + fp6 e3m2 packed storage")


# ------------------------------------------------------- int4 (WoQ) packing

def quantize_int4_blockwise(x, block_size: int = 2048):
    """Weight-only INT4: symmetric per-block quant to [-7, 7], two nibbles
    packed per int8 byte (reference ``inference/quantization`` WoQ int4 and
    ``quantize_intX.cu``). Returns (packed int8 [N/2], scales f32)."""
    flat = x.reshape(-1)
    padded, _ = _pad_to_blocks(flat, block_size)
    blocks = padded.reshape(-1, block_size).astype(jnp.float32)
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True), 1e-12) / 7.0
    q = jnp.clip(jnp.round(blocks / scales), -7, 7).astype(jnp.int8)  # [-7,7]
    q = q.reshape(-1)
    # pack: low nibble = even idx, high nibble = odd idx (offset-8 unsigned)
    u = (q + 8).astype(jnp.uint8)
    packed = (u[0::2] | (u[1::2] << 4)).astype(jnp.int8)
    return packed, scales[:, 0]


def dequantize_int4_blockwise(packed, scales, shape, block_size: int = 2048):
    """Inverse of quantize_int4_blockwise."""
    import numpy as _np
    u = packed.astype(jnp.uint8)
    lo = (u & 0xF).astype(jnp.int32) - 8
    hi = (u >> 4).astype(jnp.int32) - 8
    q = jnp.stack([lo, hi], axis=1).reshape(-1).astype(jnp.float32)
    blocks = q.reshape(-1, block_size) * scales[:, None]
    n = int(_np.prod(shape))
    return blocks.reshape(-1)[:n].reshape(shape)


registry.register("quantizer_int4", "xla", True, "weight-only int4, nibble-packed")


# ------------------------------------------------------- FP6 (e3m2) packing

# FP6-LLM's weight format (reference ``csrc/fp_quantizer/fp_quantize.cu`` +
# ``ops/fp_quantizer/quantize.py:43``): sign(1) exp(3) mantissa(2), bias 3,
# no inf/nan. Magnitude codes 0..31: m<4 are subnormals (m * 2^-4), else
# (1 + (m&3)/4) * 2^((m>>2) - 3). Max normal = 1.75 * 2^4 = 28.
_FP6_MAX = 28.0


def _fp6_encode_mag(mag):
    """Magnitude (fp32, in [0, 28]) → 5-bit magnitude code, round-to-nearest.
    The carry trick: code = E*4 + round((mag/2^(E-3) - 1)*4) rolls a mantissa
    overflow into the next exponent automatically."""
    mag = jnp.minimum(mag, _FP6_MAX)
    safe = jnp.maximum(mag, 1e-30)
    E = jnp.clip(jnp.floor(jnp.log2(safe)) + 3, 1, 7)
    man = jnp.round((mag / jnp.exp2(E - 3) - 1.0) * 4.0)
    normal_code = E * 4 + man
    sub_code = jnp.round(mag * 16.0)  # units of 2^-4; 4 rolls into E=1,M=0
    code = jnp.where(mag < 0.25, sub_code, normal_code)
    return jnp.clip(code, 0, 31).astype(jnp.uint8)


def _fp6_decode_mag(code):
    E = (code >> 2).astype(jnp.float32)
    man = (code & 0x3).astype(jnp.float32)
    sub = code.astype(jnp.float32) / 16.0
    return jnp.where(code < 4, sub, (1.0 + man / 4.0) * jnp.exp2(E - 3.0))


def quantize_fp6_blockwise(x, block_size: int = 2048):
    """Weight-only FP6 (e3m2): per-block scale maps absmax → 28, codes are
    bit-packed 4-per-3-bytes (true 6-bit storage — the quality-per-bit point
    between int4 and int8 that FP6-LLM ships). Returns
    (packed uint8 [3N/4], scales f32 [N/block])."""
    if block_size % 4:
        raise ValueError(f"block_size must be a multiple of 4, got {block_size}")
    flat = x.reshape(-1)
    padded, _ = _pad_to_blocks(flat, block_size)
    blocks = padded.reshape(-1, block_size).astype(jnp.float32)
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True),
                         1e-12) / _FP6_MAX
    scaled = blocks / scales
    codes = _fp6_encode_mag(jnp.abs(scaled))
    codes = codes | (jnp.signbit(scaled).astype(jnp.uint8) << 5)
    c = codes.reshape(-1, 4).astype(jnp.uint32)
    c0, c1, c2, c3 = c[:, 0], c[:, 1], c[:, 2], c[:, 3]
    b0 = c0 | ((c1 & 0x3) << 6)
    b1 = (c1 >> 2) | ((c2 & 0xF) << 4)
    b2 = (c2 >> 4) | (c3 << 2)
    packed = jnp.stack([b0, b1, b2], axis=1).reshape(-1).astype(jnp.uint8)
    return packed, scales[:, 0]


def dequantize_fp6_blockwise(packed, scales, shape, block_size: int = 2048,
                             dtype=jnp.float32):
    """Inverse of quantize_fp6_blockwise — shift/mask unpack + exp2 decode,
    all elementwise (XLA fuses it into the consuming matmul's operand read)."""
    import numpy as _np
    b = packed.reshape(-1, 3).astype(jnp.uint32)
    b0, b1, b2 = b[:, 0], b[:, 1], b[:, 2]
    c0 = b0 & 0x3F
    c1 = (b0 >> 6) | ((b1 & 0xF) << 2)
    c2 = (b1 >> 4) | ((b2 & 0x3) << 4)
    c3 = b2 >> 2
    codes = jnp.stack([c0, c1, c2, c3], axis=1).reshape(-1).astype(jnp.uint8)
    mag = _fp6_decode_mag(codes & 0x1F)
    vals = jnp.where(codes >> 5, -mag, mag)
    blocks = vals.reshape(-1, block_size) * scales[:, None]
    n = int(_np.prod(shape))
    return blocks.reshape(-1)[:n].reshape(shape).astype(dtype)


registry.register("quantizer_fp6", "xla", True,
                  "weight-only fp6 e3m2, 4-codes-per-3-bytes packed")
