"""Blockwise int8 quantization — the ZeRO++ / compression workhorse.

TPU-native equivalent of reference ``csrc/quantization/`` (``quantize.cu``
symmetric block quant, ``swizzled_quantize.cu`` comm-layout variant,
``quant_reduce.cu`` fused dequant+reduce for qgZ): values are grouped into
fixed-size blocks, each block scaled by absmax/127 to int8.

Used by: qwZ (quantized weight allgather), qgZ (quantized gradient
all-to-all reduce), weight-only inference quantization, 1-bit optimizer wire
format. Pallas kernel for TPU; jnp fallback elsewhere (identical numerics).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas


def _quant_kernel(x_ref, v_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)  # [rows, block]
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    v_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _pad_to_blocks(flat, block_size):
    pad = (-flat.shape[0]) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad


def quantize_int8_blockwise(x, block_size: int = 2048,
                            force_pallas: Optional[bool] = None,
                            interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quant. Returns (values int8 [N], scales
    fp32 [N/block]); padding (zeros) is included in the trailing block."""
    orig = x.shape
    flat, _ = _pad_to_blocks(x.reshape(-1), block_size)
    rows = flat.shape[0] // block_size
    blocks = flat.reshape(rows, block_size)
    if use_pallas(force_pallas) or interpret:
        tile = min(rows, 256)
        pad_r = (-rows) % tile
        if pad_r:
            blocks = jnp.pad(blocks, ((0, pad_r), (0, 0)))
        v, s = pl.pallas_call(
            _quant_kernel,
            grid=(blocks.shape[0] // tile, ),
            in_specs=[pl.BlockSpec((tile, block_size), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec((tile, block_size), lambda i: (i, 0)),
                pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(blocks.shape, jnp.int8),
                jax.ShapeDtypeStruct((blocks.shape[0], 1), jnp.float32),
            ],
            interpret=interpret,
        )(blocks)
        if pad_r:
            v, s = v[:rows], s[:rows]
    else:
        xf = blocks.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        s = jnp.where(absmax == 0, 1.0, absmax / 127.0)
        v = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return v.reshape(-1), s.reshape(-1)


def dequantize_int8_blockwise(values, scales, shape, block_size: int = 2048,
                              dtype=jnp.float32):
    """Inverse of quantize_int8_blockwise (reference dequantize.cu)."""
    rows = values.shape[0] // block_size
    x = values.reshape(rows, block_size).astype(jnp.float32) * scales.reshape(rows, 1)
    n = 1
    for d in shape:
        n *= d
    return x.reshape(-1)[:n].reshape(shape).astype(dtype)


registry.register("quantizer_int8", "pallas" if _HAS_PLTPU else "xla", True)


# ---------------------------------------------------------------- FP8/FP quant

def quantize_fp8(x, dtype=jnp.float8_e4m3fn, block_size: int = 2048):
    """Blockwise-scaled FP8 quantization.

    Reference ``csrc/fp_quantizer/fp_quantize.cu`` (FP6-LLM-style low-bit
    float formats for weights). TPU-native version targets the hardware's
    fp8 dtypes (e4m3 for weights/activations, e5m2 for gradients); blocks
    are scaled so the absmax maps to the format's max normal, preserving
    dynamic range the way the reference's per-group scales do. FP6 packing
    has no TPU dtype — e4m3 is the native equivalent tier.

    Returns (values: dtype, scales: f32 per block).
    """
    finfo_max = float(jnp.finfo(dtype).max)
    flat = x.reshape(-1)
    padded, _ = _pad_to_blocks(flat, block_size)
    blocks = padded.reshape(-1, block_size).astype(jnp.float32)
    scales = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / finfo_max
    scales = jnp.maximum(scales, 1e-12)
    values = (blocks / scales).astype(dtype)
    return values, scales[:, 0]


def dequantize_fp8(values, scales, shape, block_size: int = 2048):
    """Inverse of quantize_fp8."""
    blocks = values.astype(jnp.float32) * scales[:, None]
    import numpy as _np
    n = int(_np.prod(shape))
    return blocks.reshape(-1)[:n].reshape(shape)


registry.register("fp_quantizer", "xla", True, "fp8 e4m3/e5m2 (fp6 has no TPU dtype)")


# ------------------------------------------------------- int4 (WoQ) packing

def quantize_int4_blockwise(x, block_size: int = 2048):
    """Weight-only INT4: symmetric per-block quant to [-7, 7], two nibbles
    packed per int8 byte (reference ``inference/quantization`` WoQ int4 and
    ``quantize_intX.cu``). Returns (packed int8 [N/2], scales f32)."""
    flat = x.reshape(-1)
    padded, _ = _pad_to_blocks(flat, block_size)
    blocks = padded.reshape(-1, block_size).astype(jnp.float32)
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True), 1e-12) / 7.0
    q = jnp.clip(jnp.round(blocks / scales), -7, 7).astype(jnp.int8)  # [-7,7]
    q = q.reshape(-1)
    # pack: low nibble = even idx, high nibble = odd idx (offset-8 unsigned)
    u = (q + 8).astype(jnp.uint8)
    packed = (u[0::2] | (u[1::2] << 4)).astype(jnp.int8)
    return packed, scales[:, 0]


def dequantize_int4_blockwise(packed, scales, shape, block_size: int = 2048):
    """Inverse of quantize_int4_blockwise."""
    import numpy as _np
    u = packed.astype(jnp.uint8)
    lo = (u & 0xF).astype(jnp.int32) - 8
    hi = (u >> 4).astype(jnp.int32) - 8
    q = jnp.stack([lo, hi], axis=1).reshape(-1).astype(jnp.float32)
    blocks = q.reshape(-1, block_size) * scales[:, None]
    n = int(_np.prod(shape))
    return blocks.reshape(-1)[:n].reshape(shape)


registry.register("quantizer_int4", "xla", True, "weight-only int4, nibble-packed")
