"""Fused optimizer step kernels.

TPU-native equivalent of reference ``csrc/adam/multi_tensor_adam.cu`` (+
``fused_adam_frontend.cpp``): the whole Adam update — bias-corrected moments,
parameter write — in one pass over memory. Under XLA the optax chain already
fuses into a couple of loops, so the Pallas kernel's value is guaranteeing
the single-pass HBM traffic pattern (one read of p/m/v/g, one write of
p/m/v) regardless of surrounding graph.

The "multi-tensor" aspect of the reference (kernel launch amortization over
many small tensors) is native here: the caller flattens the param pytree into
one ravelled buffer per state (jnp.concatenate), the kernel runs over blocks.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas


def _launch_flat(kernel, tensors, scalars, out_dtypes, interpret):
    """Run `kernel` over flat [N] buffers reshaped to a (rows, 2048) layout.

    The Mosaic tiling contract wants the last two block dims ÷(8, 128):
    lanes=2048 (16×128), row tiles of up to 64 — 7 live (tile, 2048) fp32
    buffers × double buffering fit ~16MB VMEM. Scalars ride in SMEM.
    Returns the outputs as flat [N] buffers.
    """
    n = tensors[0].shape[0]
    lanes = 2048
    pad = (-n) % lanes
    def _pad(x):
        return jnp.pad(x, (0, pad)) if pad else x
    t2 = [_pad(t).reshape(-1, lanes) for t in tensors]
    rows = t2[0].shape[0]
    tile = min(64, rows) if rows % 8 == 0 else rows
    while rows % tile != 0:
        tile //= 2
    tile = max(tile, 1)

    blk = lambda i: (i, 0)
    tile_spec = pl.BlockSpec((tile, lanes), blk)
    scalar_spec = (pl.BlockSpec(memory_space=pltpu.SMEM) if _HAS_PLTPU
                   else pl.BlockSpec((1, )))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // tile, ),
        in_specs=[tile_spec] * len(t2) + [scalar_spec] * len(scalars),
        out_specs=[tile_spec] * len(out_dtypes),
        out_shape=[jax.ShapeDtypeStruct(t2[0].shape, dt) for dt in out_dtypes],
        interpret=interpret,
    )(*t2, *scalars)
    return tuple(o.reshape(-1)[:n] for o in outs)


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, lr_ref, step_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps, wd):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1 - b1) * g
    v = b2 * v_ref[:] + (1 - b2) * g * g
    step = step_ref[0]
    # b**step as exp(step*log(b)): Mosaic has no powf lowering
    bc1 = 1 - jnp.exp(step * np.log(b1))
    bc2 = 1 - jnp.exp(step * np.log(b2))
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd:
        update = update + wd * p
    lr = lr_ref[0]
    po_ref[:] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_step(params, grads, m, v, lr, step,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                    weight_decay: float = 0.0, block: int = 8 * 2048,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One AdamW step over flat fp32 buffers [N]. Returns (params, m, v).

    `lr` scalar, `step` the 1-based step count (bias correction).
    """
    n = params.shape[0]
    lr_arr = jnp.asarray([lr], jnp.float32).reshape(1)
    step_arr = jnp.asarray([step], jnp.float32).reshape(1)

    if not (use_pallas(force_pallas) or interpret):
        g = grads.astype(jnp.float32)
        m_n = b1 * m + (1 - b1) * g
        v_n = b2 * v + (1 - b2) * g * g
        bc1 = 1 - b1 ** step_arr[0]
        bc2 = 1 - b2 ** step_arr[0]
        upd = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if weight_decay:
            upd = upd + weight_decay * params.astype(jnp.float32)
        return (params - lr_arr[0] * upd).astype(params.dtype), m_n, v_n

    kernel = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=weight_decay)
    return _launch_flat(kernel, (params, grads, m, v), (lr_arr, step_arr),
                        (params.dtype, jnp.float32, jnp.float32), interpret)


def _lion_kernel(p_ref, g_ref, m_ref, lr_ref, po_ref, mo_ref, *, b1, b2, wd):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:]
    u = jnp.sign(b1 * m + (1 - b1) * g)
    if wd:
        u = u + wd * p
    po_ref[:] = (p - lr_ref[0] * u).astype(po_ref.dtype)
    mo_ref[:] = b2 * m + (1 - b2) * g


def fused_lion_step(params, grads, m, lr,
                    b1: float = 0.9, b2: float = 0.99,
                    weight_decay: float = 0.0,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """One Lion step over flat buffers [N]. Returns (params, m).

    optax.lion semantics (sign of the b1-interpolated momentum, decoupled
    weight decay); single-pass HBM traffic like the reference's
    ``csrc/lion/multi_tensor_lion.cu``.
    """
    n = params.shape[0]
    lr_arr = jnp.asarray([lr], jnp.float32).reshape(1)

    if not (use_pallas(force_pallas) or interpret):
        g = grads.astype(jnp.float32)
        u = jnp.sign(b1 * m + (1 - b1) * g)
        if weight_decay:
            u = u + weight_decay * params.astype(jnp.float32)
        return (params - lr_arr[0] * u).astype(params.dtype), b2 * m + (1 - b2) * g

    kernel = functools.partial(_lion_kernel, b1=b1, b2=b2, wd=weight_decay)
    return _launch_flat(kernel, (params, grads, m), (lr_arr, ),
                        (params.dtype, jnp.float32), interpret)


def _lamb_update_kernel(g_ref, m_ref, v_ref, step_ref,
                        uo_ref, mo_ref, vo_ref, *, b1, b2, eps):
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1 - b1) * g
    v = b2 * v_ref[:] + (1 - b2) * g * g
    step = step_ref[0]
    bc1 = 1 - jnp.exp(step * np.log(b1))
    bc2 = 1 - jnp.exp(step * np.log(b2))
    uo_ref[:] = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_lamb_step(params, grads, m, v, lr, step,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
                    weight_decay: float = 0.0,
                    segments: Optional[Tuple[int, ...]] = None,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One LAMB step over flat buffers [N]. Returns (params, m, v).

    The reference's ``csrc/lamb/fused_lamb_cuda_kernel.cu`` runs two passes:
    the Adam-shaped update plus per-tensor reduction, then the trust-ratio
    scaled write. Same structure here: a Pallas pass produces the
    bias-corrected update and new moments (one read of g/m/v, one write of
    u/m/v); the per-tensor trust ratio ||p||/||u + wd*p|| is a pair of XLA
    segment reductions fused into the scaled parameter write.

    `segments`: tensor boundary offsets into the flat buffer (e.g.
    (0, n1, n1+n2, ..., N)); trust ratios are computed per segment, matching
    the reference's per-tensor launches. Default: one segment (whole buffer).
    """
    n = params.shape[0]
    step_arr = jnp.asarray([step], jnp.float32).reshape(1)

    if use_pallas(force_pallas) or interpret:
        kernel = functools.partial(_lamb_update_kernel, b1=b1, b2=b2, eps=eps)
        u, m_n, v_n = _launch_flat(kernel, (grads, m, v), (step_arr, ),
                                   (jnp.float32, jnp.float32, jnp.float32), interpret)
    else:
        g = grads.astype(jnp.float32)
        m_n = b1 * m + (1 - b1) * g
        v_n = b2 * v + (1 - b2) * g * g
        bc1 = 1 - b1 ** step_arr[0]
        bc2 = 1 - b2 ** step_arr[0]
        u = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)

    pf = params.astype(jnp.float32)
    if weight_decay:
        u = u + weight_decay * pf

    if segments is None or len(segments) <= 2:
        pn = jnp.sqrt(jnp.sum(pf * pf))
        un = jnp.sqrt(jnp.sum(u * u))
        trust = jnp.where((pn > 0) & (un > 0), pn / jnp.maximum(un, 1e-30), 1.0)
    else:
        seg_ids = np.zeros(n, np.int32)
        for i in range(1, len(segments) - 1):
            seg_ids[segments[i]:] += 1
        nseg = len(segments) - 1
        seg_ids = jnp.asarray(seg_ids)
        pn = jnp.sqrt(jax.ops.segment_sum(pf * pf, seg_ids, num_segments=nseg))
        un = jnp.sqrt(jax.ops.segment_sum(u * u, seg_ids, num_segments=nseg))
        trust_seg = jnp.where((pn > 0) & (un > 0), pn / jnp.maximum(un, 1e-30), 1.0)
        trust = trust_seg[seg_ids]

    lr_arr = jnp.asarray([lr], jnp.float32).reshape(1)
    return (pf - lr_arr[0] * trust * u).astype(params.dtype), m_n, v_n


registry.register("fused_adam", "pallas" if _HAS_PLTPU else "xla", True)
registry.register("fused_lion", "pallas" if _HAS_PLTPU else "xla", True)
registry.register("fused_lamb", "pallas" if _HAS_PLTPU else "xla", True)
