"""Fused optimizer step kernels.

TPU-native equivalent of reference ``csrc/adam/multi_tensor_adam.cu`` (+
``fused_adam_frontend.cpp``): the whole Adam update — bias-corrected moments,
parameter write — in one pass over memory. Under XLA the optax chain already
fuses into a couple of loops, so the Pallas kernel's value is guaranteeing
the single-pass HBM traffic pattern (one read of p/m/v/g, one write of
p/m/v) regardless of surrounding graph.

The "multi-tensor" aspect of the reference (kernel launch amortization over
many small tensors) is native here: the caller flattens the param pytree into
one ravelled buffer per state (jnp.concatenate), the kernel runs over blocks.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, lr_ref, step_ref,
                 po_ref, mo_ref, vo_ref, *, b1, b2, eps, wd):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1 - b1) * g
    v = b2 * v_ref[:] + (1 - b2) * g * g
    step = step_ref[0]
    # b**step as exp(step*log(b)): Mosaic has no powf lowering
    bc1 = 1 - jnp.exp(step * np.log(b1))
    bc2 = 1 - jnp.exp(step * np.log(b2))
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd:
        update = update + wd * p
    lr = lr_ref[0]
    po_ref[:] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adam_step(params, grads, m, v, lr, step,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                    weight_decay: float = 0.0, block: int = 8 * 2048,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One AdamW step over flat fp32 buffers [N]. Returns (params, m, v).

    `lr` scalar, `step` the 1-based step count (bias correction).
    """
    n = params.shape[0]
    lr_arr = jnp.asarray([lr], jnp.float32).reshape(1)
    step_arr = jnp.asarray([step], jnp.float32).reshape(1)

    if not (use_pallas(force_pallas) or interpret):
        g = grads.astype(jnp.float32)
        m_n = b1 * m + (1 - b1) * g
        v_n = b2 * v + (1 - b2) * g * g
        bc1 = 1 - b1 ** step_arr[0]
        bc2 = 1 - b2 ** step_arr[0]
        upd = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if weight_decay:
            upd = upd + weight_decay * params.astype(jnp.float32)
        return (params - lr_arr[0] * upd).astype(params.dtype), m_n, v_n

    # 2D layout: lanes=2048 (16×128), row tiles of up to 256 (÷8) — the
    # Mosaic tiling contract wants the last two block dims ÷(8, 128)
    lanes = 2048
    pad = (-n) % lanes
    def _pad(x):
        return jnp.pad(x, (0, pad)) if pad else x
    p2, g2, m2, v2 = (_pad(t).reshape(-1, lanes) for t in (params, grads, m, v))
    rows = p2.shape[0]
    # 7 live (tile, 2048) fp32 buffers × double buffering must fit ~16MB VMEM
    tile = min(64, rows) if rows % 8 == 0 else rows
    while rows % tile != 0:
        tile //= 2
    tile = max(tile, 1)

    kernel = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=weight_decay)
    blk = lambda i: (i, 0)
    po, mo, vo = pl.pallas_call(
        kernel,
        grid=(rows // tile, ),
        in_specs=[
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec(memory_space=pltpu.SMEM) if _HAS_PLTPU else pl.BlockSpec((1, )),
            pl.BlockSpec(memory_space=pltpu.SMEM) if _HAS_PLTPU else pl.BlockSpec((1, )),
        ],
        out_specs=[
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec((tile, lanes), blk),
            pl.BlockSpec((tile, lanes), blk),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, params.dtype),
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        ],
        interpret=interpret,
    )(p2, g2, m2, v2, lr_arr, step_arr)
    out = tuple(t.reshape(-1)[:n] for t in (po, mo, vo))
    return out


registry.register("fused_adam", "pallas" if _HAS_PLTPU else "xla", True)
