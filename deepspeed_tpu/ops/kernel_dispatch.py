"""Shape-aware attention kernel dispatch.

The round-5 chip breakdown proved the static kernel choice wrong at the
bench shape: the Pallas flash *forward* lost to XLA's fused attention
(62.9 ms vs 42.7 ms at hd64/seq1024) while the flash *backward* is the leg
the Pallas pair actually wins (no [S, S] score materialization in the
recompute).  DeepCompile (arXiv:2504.09983) argues exactly this: profile-
guided, per-shape kernel selection should replace static choices in
distributed training stacks.

This module picks the forward and backward implementations *independently*
per (shape, dtype, causal/window/softcap flags, device kind).  Precedence
per leg, strongest first:

1. explicit ``impl_fwd``/``impl_bwd`` kwargs on ``flash_attention`` (tests,
   the sweep tool);
2. ``DS_TPU_ATTN_FWD`` / ``DS_TPU_ATTN_BWD`` env (``xla|pallas|folded``);
3. legacy ``DS_TPU_FLASH_FOLDED``: nonzero forces the folded Pallas pair on
   BOTH legs (existing A/B scripts and tests depend on that); ``0`` pins
   the per-head variant for any leg that resolves to Pallas;
4. a *measured* entry in the persistent autotune cache
   (``autotune_cache.py``, written by ``bin/ds_kernel_tune``);
5. the built-in heuristic table below (which encodes the measured
   42.7 < 62.9 ms fwd result: XLA fused forward at hd64 / seq >= 1024,
   Pallas backward always);
6. the deprecated ``.perf/FOLDED_PROVEN`` sentinel — still honored as a
   folded-variant preference so an existing promotion isn't silently
   dropped, but it logs a deprecation warning pointing at the cache.

Blocks follow the same idea: explicit args > ``DS_TPU_FLASH_BLOCKS`` env >
measured cache blocks > per-head_dim defaults (the round-5 sweep result
(256, 512) at hd64).
"""

import functools
import os
from typing import NamedTuple, Optional

from .autotune_cache import get_cache
from ..utils.logging import logger

IMPL_XLA = "xla"
IMPL_PALLAS = "pallas"  # per-head kernels (ops/attention.py)
IMPL_FOLDED = "folded"  # head-folded kernels (ops/attention_folded.py)
_IMPLS = (IMPL_XLA, IMPL_PALLAS, IMPL_FOLDED)

# head_dim -> default (block_q, block_k).  hd64 = (256, 512) measured on
# v5e 8/1: +20% over (256, 256) on the identical bench program
# (.perf/flash_256x512_r5_0801T1906.out).
BLOCK_TABLE = {64: (256, 512), 128: (128, 128)}
DEFAULT_BLOCKS = (128, 128)

# candidate (block_q, block_k) grid the offline sweep times, beyond the
# defaults — the round-5 sweep died at the window edge before reaching them
SWEEP_BLOCKS = ((256, 512), (512, 512), (512, 1024), (1024, 1024),
                (128, 128), (256, 256))


class ShapeSig(NamedTuple):
    """Static trace-time facts a dispatch decision may depend on."""
    batch: int
    seq_q: int
    seq_k: int
    heads: int
    kv_heads: int
    head_dim: int
    dtype: str
    causal: bool
    windowed: bool
    softcapped: bool


class Decision(NamedTuple):
    """One leg's resolved choice. ``source`` records provenance for the
    artifacts: explicit | env | legacy-env | measured | heuristic."""
    impl: str
    block_q: int
    block_k: int
    source: str


def make_sig(q_shape, kv_heads: int, seq_k: int, dtype, causal: bool,
             window, softcap) -> ShapeSig:
    b, sq, h, d = q_shape
    return ShapeSig(batch=int(b), seq_q=int(sq), seq_k=int(seq_k),
                    heads=int(h), kv_heads=int(kv_heads), head_dim=int(d),
                    dtype=str(dtype), causal=bool(causal),
                    windowed=window is not None,
                    softcapped=softcap is not None)


def signature(leg: str, sig: ShapeSig, device_kind: str) -> str:
    """Cache key: leg + device kind + the full shape signature.  Versioned
    at the file level (autotune_cache.CACHE_VERSION), so this string only
    needs to be collision-free, not forward-compatible."""
    return (f"{leg}|{device_kind}|b{sig.batch}|sq{sig.seq_q}|sk{sig.seq_k}"
            f"|h{sig.heads}|kv{sig.kv_heads}|d{sig.head_dim}|{sig.dtype}"
            f"|c{int(sig.causal)}|w{int(sig.windowed)}"
            f"|sc{int(sig.softcapped)}")


def device_kind() -> str:
    """Device kind string for cache keys ("TPU v5e", "cpu", ...).  Interpret
    mode keys as "interpret" so CPU sweep results never masquerade as chip
    measurements."""
    try:
        import jax
        d = jax.devices()[0]
        return getattr(d, "device_kind", None) or d.platform
    except Exception:  # noqa: BLE001 — no backend yet
        return "unknown"


@functools.cache
def _sentinel_folded() -> bool:
    """Deprecated ``.perf/FOLDED_PROVEN`` sentinel (pre-dispatch silicon A/B
    promotion).  Still read as a variant preference so an earned promotion
    survives the transition, but the tracked autotune cache is the
    replacement — warn once."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..", ".perf", "FOLDED_PROVEN")
    if os.path.exists(path):
        logger.warning(
            ".perf/FOLDED_PROVEN is deprecated: commit a measured entry to "
            "the attention autotune cache instead (bin/ds_kernel_tune); the "
            "sentinel is honored only as a folded-variant preference when "
            "no measurement exists")
        return True
    return False


def _env_impl(name: str) -> Optional[str]:
    val = os.environ.get(name, "").strip().lower()
    if not val:
        return None
    if val not in _IMPLS:
        logger.warning(f"{name}={val!r} ignored (want one of {_IMPLS})")
        return None
    return val


def _variant_preference() -> Optional[str]:
    """Which Pallas variant (per-head vs folded) a Pallas leg should use
    when nothing shape-specific decided it: legacy env wins, then the
    deprecated sentinel."""
    env = os.environ.get("DS_TPU_FLASH_FOLDED")
    if env is not None:
        return IMPL_FOLDED if env not in ("", "0") else IMPL_PALLAS
    if _sentinel_folded():
        return IMPL_FOLDED
    return None


def _env_blocks() -> Optional[tuple]:
    env = os.environ.get("DS_TPU_FLASH_BLOCKS")
    if not env:
        return None
    try:
        bq, bk = (int(x) for x in env.split(","))
        return bq, bk
    except ValueError:
        logger.warning(f"DS_TPU_FLASH_BLOCKS={env!r} ignored (want 'bq,bk')")
        return None


def default_blocks(head_dim: int) -> tuple:
    return BLOCK_TABLE.get(head_dim, DEFAULT_BLOCKS)


def _heuristic_impl(leg: str, sig: ShapeSig) -> str:
    """Built-in table when no measurement exists.

    Forward: XLA's fused softmax-attention beat the Pallas flash forward at
    the bench shape (42.7 vs 62.9 ms, hd64/seq1024 — docs/PERF_NOTES.md);
    the regime is "scores fit comfortably and XLA fuses the whole chain",
    which holds for hd64 at seq >= 1024 on sequences that are not
    window-limited.  Windowed shapes keep the Pallas forward: it skips
    out-of-window blocks entirely, XLA still materializes [S, S].

    Backward: Pallas flash always — the two-pass recompute never
    materializes scores, which is where the memory and time win lives
    (the same breakdown measured the pallas pair ahead on fwd+bwd).
    """
    if leg == "fwd":
        if (sig.head_dim <= 64 and sig.seq_k >= 1024 and not sig.windowed):
            return IMPL_XLA
        return IMPL_PALLAS
    return IMPL_PALLAS


def resolve_leg(leg: str, sig: ShapeSig, kind: Optional[str] = None, *,
                explicit_impl: Optional[str] = None,
                explicit_blocks: Optional[tuple] = None,
                pallas_only: bool = False) -> Decision:
    """Resolve one leg ("fwd" | "bwd") to a Decision.  ``pallas_only``
    (force_pallas=True callers: kernel-math tests) restricts the choice to
    the Pallas variants — an XLA pick degrades to the per-head kernel."""
    kind = kind if kind is not None else device_kind()
    variant = _variant_preference()

    impl = None
    source = None
    if explicit_impl is not None:
        assert explicit_impl in _IMPLS, explicit_impl
        impl, source = explicit_impl, "explicit"
    if impl is None:
        env = _env_impl("DS_TPU_ATTN_FWD" if leg == "fwd" else "DS_TPU_ATTN_BWD")
        if env is not None:
            impl, source = env, "env"
    if impl is None and os.environ.get("DS_TPU_FLASH_FOLDED") not in (None, "", "0"):
        # legacy env: the folded kernels run end to end (both legs)
        impl, source = IMPL_FOLDED, "legacy-env"

    measured = None
    if impl is None:
        measured = get_cache().lookup(signature(leg, sig, kind))
        if measured and measured.get("impl") in _IMPLS:
            impl, source = measured["impl"], "measured"
        else:
            measured = None
    if impl is None:
        impl, source = _heuristic_impl(leg, sig), "heuristic"
        if impl == IMPL_PALLAS and variant == IMPL_FOLDED:
            impl = IMPL_FOLDED

    if pallas_only and impl == IMPL_XLA:
        impl = variant or IMPL_PALLAS
        source += "+pallas-forced"

    # blocks: explicit > env > measured > head_dim default
    blocks = explicit_blocks or _env_blocks()
    if blocks is None and measured is not None:
        try:
            blocks = (int(measured["block_q"]), int(measured["block_k"]))
        except (KeyError, TypeError, ValueError):
            blocks = None
    if blocks is None:
        blocks = default_blocks(sig.head_dim)
    return Decision(impl=impl, block_q=int(blocks[0]), block_k=int(blocks[1]),
                    source=source)


def resolve(sig: ShapeSig, kind: Optional[str] = None, *,
            impl_fwd: Optional[str] = None, impl_bwd: Optional[str] = None,
            blocks: Optional[tuple] = None, pallas_only: bool = False):
    """(fwd Decision, bwd Decision) for one attention call site."""
    fwd = resolve_leg("fwd", sig, kind, explicit_impl=impl_fwd,
                      explicit_blocks=blocks, pallas_only=pallas_only)
    bwd = resolve_leg("bwd", sig, kind, explicit_impl=impl_bwd,
                      explicit_blocks=blocks, pallas_only=pallas_only)
    return fwd, bwd


def describe(fwd: Decision, bwd: Decision) -> str:
    """Compact per-leg note for bench unit tags / artifacts, e.g.
    ``attn[fwd=xla:heuristic,bwd=pallas@256x512:measured]``."""

    def leg(d: Decision) -> str:
        blocks = ("" if d.impl == IMPL_XLA
                  else f"@{d.block_q}x{d.block_k}")
        return f"{d.impl}{blocks}:{d.source}"

    return f"attn[fwd={leg(fwd)},bwd={leg(bwd)}]"


def table_source() -> str:
    """One line for ds_report: where dispatch decisions come from."""
    return get_cache().source_description()


def resolved_note(batch=8, seq=1024, heads=16, kv_heads=None, head_dim=64,
                  dtype="bfloat16", causal=True,
                  kind: Optional[str] = None) -> str:
    """The per-leg dispatch note at a given (default: THE bench) shape —
    reporting surfaces call this so every banked artifact records which
    kernels actually ran."""
    sig = make_sig((batch, seq, heads, head_dim),
                   kv_heads if kv_heads is not None else heads, seq, dtype,
                   causal, None, None)
    return describe(*resolve(sig, kind))
