"""Memory-efficient (chunked) unembed + cross-entropy.

TPU analog of the reference's fused-softmax/logit kernels for large vocab
(``csrc/transformer/inference/csrc/softmax.cu`` handles the on-device
softmax; training CE in the reference stays torch — at 32k–256k vocab the
``[tokens, vocab]`` logits tensor is the single biggest training activation:
bs16 x seq1024 x 32k fp32 is 2.1 GB saved for backward, 8+ GB at Gemma's
256k).

This op never materializes the full logits matrix in either pass:

- forward: ``lax.scan`` over vocab chunks; each step computes the chunk's
  logits ``x @ w[:, c]`` on the MXU and folds them into a running online
  logsumexp (m, s) plus the gold-label logit — O(T) state, O(T * chunk)
  transient.
- backward (``jax.custom_vjp``): re-runs the same chunk sweep, rebuilding
  ``p_c = exp(logits_c - lse)`` and accumulating ``dx += dl_c @ w_cᵀ``,
  ``dw_c = xᵀ @ dl_c`` per chunk — the one extra chunk-matmul sweep costs
  ~2% of a 0.4B-model step, the 2.1 GB saved activation costs nothing.

Cohere ``logit_scale`` and Gemma-2 ``final_logit_softcapping`` are applied
per chunk (elementwise), so the models that most need chunking (Gemma's
256k vocab) keep their exact logit semantics.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _num_chunks(V: int, chunk: int) -> int:
    return -(-V // chunk)


def _pad_to_chunks(w, bias, chunk):
    """Right-pad the vocab axis to a chunk multiple: dynamic_slice CLAMPS
    out-of-range starts (the last ragged chunk would silently re-read
    earlier columns), so every slice must be in-bounds by construction."""
    V = w.shape[1]
    Vp = _num_chunks(V, chunk) * chunk
    if Vp != V:
        w = jnp.pad(w, ((0, 0), (0, Vp - V)))
        if bias is not None:
            bias = jnp.pad(bias, (0, Vp - V))
    return w, bias


def _chunk_logits(x, w, bias, c0, chunk, V, logit_scale, softcap,
                  compute_dtype):
    """fp32 logits for vocab columns [c0, c0+chunk) of the PADDED w
    (+scale/softcap), plus the tanh(l/cap) needed for the softcap chain
    rule; ``V`` is the true vocab size for masking the padded tail."""
    wc = jax.lax.dynamic_slice_in_dim(w, c0, chunk, axis=1)
    lc = jax.lax.dot_general(x.astype(compute_dtype), wc.astype(compute_dtype),
                             (((1, ), (0, )), ((), ())),
                             preferred_element_type=jnp.float32)
    if bias is not None:
        lc = lc + jax.lax.dynamic_slice_in_dim(
            bias.astype(jnp.float32), c0, chunk, axis=0)
    if logit_scale is not None:
        lc = lc * jnp.float32(logit_scale)
    t = None
    if softcap is not None:
        t = jnp.tanh(lc / softcap)
        lc = softcap * t
    # mask padded columns (V not divisible by chunk) out of the softmax
    col = c0 + jax.lax.broadcasted_iota(jnp.int32, lc.shape, 1)
    lc = jnp.where(col < V, lc, -jnp.inf)
    return lc, t, col


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def chunked_unembed_ce(x, w, bias, targets, chunk: int,
                       logit_scale: Optional[float] = None,
                       softcap: Optional[float] = None,
                       compute_dtype=jnp.bfloat16):
    """Per-token NLL of ``softmax(x @ w + bias)`` without materializing the
    logits. ``x`` [T, H], ``w`` [H, V], ``bias`` [V] or None, ``targets``
    [T] int (callers mask ignore_index outside). Returns nll [T] fp32."""
    nll, _ = _fwd_sweep(x, w, bias, targets, chunk, logit_scale, softcap,
                        compute_dtype)
    return nll


def _fwd_sweep(x, w, bias, targets, chunk, logit_scale, softcap, compute_dtype):
    T = x.shape[0]
    V = w.shape[1]
    nc = _num_chunks(V, chunk)
    wp, biasp = _pad_to_chunks(w, bias, chunk)

    def step(carry, ci):
        m, s, gold = carry
        lc, _, col = _chunk_logits(x, wp, biasp, ci * chunk, chunk, V,
                                   logit_scale, softcap, compute_dtype)
        m_new = jnp.maximum(m, lc.max(axis=-1))
        # exp(-inf - -inf) guards: a fully-masked chunk must not poison s
        corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m - m_new))
        s = s * corr + jnp.where(jnp.isneginf(lc), 0.0,
                                 jnp.exp(lc - m_new[:, None])).sum(axis=-1)
        hit = col == targets[:, None]
        gold = gold + jnp.where(hit, jnp.where(jnp.isneginf(lc), 0.0, lc),
                                0.0).sum(axis=-1)
        return (m_new, s, gold), None

    init = (jnp.full((T, ), -jnp.inf, jnp.float32),
            jnp.zeros((T, ), jnp.float32),
            jnp.zeros((T, ), jnp.float32))
    (m, s, gold), _ = jax.lax.scan(step, init, jnp.arange(nc))
    lse = m + jnp.log(s)
    return lse - gold, (m, s)


def _ce_fwd(x, w, bias, targets, chunk, logit_scale, softcap, compute_dtype):
    nll, (m, s) = _fwd_sweep(x, w, bias, targets, chunk, logit_scale, softcap,
                             compute_dtype)
    lse = m + jnp.log(s)
    return nll, (x, w, bias, targets, lse)


def _ce_bwd(chunk, logit_scale, softcap, compute_dtype, res, g):
    x, w, bias, targets, lse = res
    V = w.shape[1]
    nc = _num_chunks(V, chunk)
    T, H = x.shape

    wp, biasp = _pad_to_chunks(w, bias, chunk)

    def step(carry, ci):
        dx, dw, dbias = carry
        c0 = ci * chunk
        lc, t, col = _chunk_logits(x, wp, biasp, c0, chunk, V,
                                   logit_scale, softcap, compute_dtype)
        p = jnp.where(jnp.isneginf(lc), 0.0, jnp.exp(lc - lse[:, None]))
        dl = (p - (col == targets[:, None]).astype(jnp.float32)) * g[:, None]
        # chain back through softcap then logit_scale (applied in that order
        # forward: scale -> softcap), zeroing padded columns
        if softcap is not None:
            dl = dl * (1.0 - t * t)
        if logit_scale is not None:
            dl = dl * jnp.float32(logit_scale)
        dl = jnp.where(col < V, dl, 0.0)
        wc = jax.lax.dynamic_slice_in_dim(wp, c0, chunk, axis=1)
        dx = dx + jax.lax.dot_general(
            dl.astype(compute_dtype), wc.astype(compute_dtype),
            (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        dwc = jax.lax.dot_general(
            x.astype(compute_dtype), dl.astype(compute_dtype),
            (((0, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, dwc.astype(dw.dtype), c0, axis=1)
        if dbias is not None:
            dbias = jax.lax.dynamic_update_slice_in_dim(
                dbias, dl.sum(axis=0).astype(dbias.dtype), c0, axis=0)
        return (dx, dw, dbias), None

    Vp = nc * chunk
    init = (jnp.zeros((T, H), jnp.float32),
            jnp.zeros((H, Vp), jnp.float32),
            None if bias is None else jnp.zeros((Vp, ), jnp.float32))
    (dx, dw, dbias), _ = jax.lax.scan(step, init, jnp.arange(nc))
    dx = dx.astype(x.dtype)
    dw = dw[:, :V].astype(w.dtype)
    dbias = None if bias is None else dbias[:V].astype(bias.dtype)
    return dx, dw, dbias, None


chunked_unembed_ce.defvjp(_ce_fwd, _ce_bwd)


def chunked_cross_entropy_loss(x, w, bias, labels, chunk: int,
                               ignore_index: int = -100,
                               logit_scale: Optional[float] = None,
                               softcap: Optional[float] = None,
                               compute_dtype=jnp.bfloat16):
    """Token-mean causal-LM CE (shift-by-one, ignore_index) over a streamed
    unembed — drop-in for ``models.llama.cross_entropy_loss`` fed hidden
    states instead of logits. ``x`` [B, S, H], ``labels`` [B, S]."""
    B, S, H = x.shape
    xs = x[:, :-1].reshape(B * (S - 1), H)
    tg = labels[:, 1:].reshape(B * (S - 1))
    mask = (tg != ignore_index).astype(jnp.float32)
    tg = jnp.where(tg == ignore_index, 0, tg)
    nll = chunked_unembed_ce(xs, w, bias, tg, chunk, logit_scale, softcap,
                             compute_dtype)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
