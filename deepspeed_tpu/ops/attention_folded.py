"""Head-folded flash attention (flag-gated experiment, ``DS_TPU_FLASH_FOLDED=1``).

Same math as ``ops/attention.py``'s kernels, restructured the way the 8/1
xprof trace demands: that trace showed the per-head flash kernels at 70% of
train-step device time for ~6% of model FLOPs — per-grid-step fixed cost
(~50us) over ``B*KV x num_q x num_kv`` tiny steps. Here ONE grid step
processes ALL kv heads (static in-kernel unroll, the restructure that fixed
the paged decode kernel):

- grid ``(B, num_q, num_kv)`` — KV leaves the grid entirely;
- q/o/do stay in their NATURAL ``[B, S, H, D]`` layout (block minor dims
  (H, D): sublane mult-of-8-or-equal, lane == array dim — Mosaic-legal; the
  per-head path also paid 6 host-side transposes per call in ``_regroup``,
  which all disappear);
- k/v fold to ``[B, S, KV*D]`` (free reshape; lane == array dim blocks),
  per-head slices are STATIC lane offsets inside the kernel;
- positional masks build once per step and are shared across heads; the
  interior/edge specialization (full blocks skip the mask chain) carries
  over.

The proven per-head kernels stay the default until this variant has run on
real silicon (a chip-session rung A/Bs them); interpret-mode fuzz pins
numerics equality either way.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30
LSE_MASKED = -1e30  # matches attention.py's fully-masked-row marker


def _positions(ng_shape, block_q, block_k, qi, ki, groups):
    """(q_pos, k_pos) [NG, BK] grids for one tile; rows are q-major
    (row = q_row * G + g)."""
    r = jax.lax.broadcasted_iota(jnp.int32, ng_shape, 0)
    q_pos = qi * block_q + r // groups
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, ng_shape, 1)
    return q_pos, k_pos


def _tile_conds(causal, window, block_q, block_k, qi, ki):
    """(live, interior) for the (qi, ki) tile — live: any pair unmasked;
    interior: every pair unmasked (skip the mask chain)."""
    live = True
    interior = True
    if causal:
        live = ki * block_k <= qi * block_q + block_q - 1
        interior = ki * block_k + block_k - 1 <= qi * block_q
    if window is not None:
        live = live & (ki * block_k + block_k - 1
                       >= qi * block_q - (window - 1))
        interior = interior & (
            qi * block_q + block_q - 1 - ki * block_k <= window - 1)
    return live, interior


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s,
                *, scale, causal, block_q, block_k, num_kv, num_heads: int,
                groups: int, window=None, softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    D = q_ref.shape[-1]
    bq = q_ref.shape[1]
    G = groups
    KV = num_heads // G
    ng = bq * G

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def _compute(masked):
        if masked and (causal or window is not None):
            q_pos, k_pos = _positions((ng, block_k), block_q, block_k,
                                      qi, ki, G)
            kill = k_pos > q_pos if causal else jnp.zeros((ng, block_k), bool)
            if window is not None:
                kill = kill | (q_pos - k_pos >= window)
        for h in range(KV):  # static unroll: one k/v DMA, all heads
            q = q_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, D)
            k = k_ref[0, :, h * D:(h + 1) * D]  # [BK, D] static lane slice
            v = v_ref[0, :, h * D:(h + 1) * D]
            s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if softcap is not None:
                from .attention import softcap_scores
                s = softcap_scores(s, softcap)
            if masked and (causal or window is not None):
                s = jnp.where(kill, NEG_INF, s)
            r = slice(h * ng, (h + 1) * ng)
            m_prev, l_prev = m_s[r], l_s[r]
            m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            m_safe = jnp.where(m_cur <= NEG_INF, 0.0, m_cur)
            p = jnp.exp(s - m_safe)
            if masked:
                p = jnp.where(s <= NEG_INF, 0.0, p)
            corr = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF,
                                     m_prev - m_safe))
            l_cur = l_prev * corr + p.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                     (((1, ), (0, )), ((), ())),
                                     preferred_element_type=jnp.float32)
            acc[r] = acc[r] * corr + pv
            m_s[r] = m_cur
            l_s[r] = l_cur

    live, interior = _tile_conds(causal, window, block_q, block_k, qi, ki)
    if live is True:
        _compute(masked=False)
    else:
        @pl.when(live & interior)
        def _():
            _compute(masked=False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        for h in range(KV):
            r = slice(h * ng, (h + 1) * ng)
            l = l_s[r]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, h * G:(h + 1) * G, :] = \
                (acc[r] / safe_l).reshape(bq, G, D).astype(o_ref.dtype)
            m_safe = jnp.where(m_s[r] <= NEG_INF, 0.0, m_s[r])
            lse = jnp.where(l == 0.0, LSE_MASKED, m_safe + jnp.log(safe_l))
            lse_ref[0, :, h * G:(h + 1) * G, :] = lse.reshape(bq, G, 1)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k, num_kv,
               num_heads: int, groups: int, window=None, softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    D = q_ref.shape[-1]
    bq = q_ref.shape[1]
    G = groups
    KV = num_heads // G
    ng = bq * G

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute(masked):
        if masked and (causal or window is not None):
            q_pos, k_pos = _positions((ng, block_k), block_q, block_k,
                                      qi, ki, G)
            kill = k_pos > q_pos if causal else jnp.zeros((ng, block_k), bool)
            if window is not None:
                kill = kill | (q_pos - k_pos >= window)
        for h in range(KV):
            q = q_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, D)
            do = do_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, D)
            lse = lse_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, 1)
            delta = delta_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, 1)
            k = k_ref[0, :, h * D:(h + 1) * D]
            v = v_ref[0, :, h * D:(h + 1) * D]
            s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if softcap is not None:
                t = jnp.tanh(s / softcap)
                s = softcap * t
            if masked and (causal or window is not None):
                s = jnp.where(kill, NEG_INF, s)
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(s <= NEG_INF, 0.0, p)
            dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            if softcap is not None:
                ds = ds * (1.0 - t * t)
            r = slice(h * ng, (h + 1) * ng)
            dq_acc[r] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)

    live, interior = _tile_conds(causal, window, block_q, block_k, qi, ki)
    if live is True:
        _compute(masked=False)
    else:
        @pl.when(live & interior)
        def _():
            _compute(masked=False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        for h in range(KV):
            r = slice(h * ng, (h + 1) * ng)
            dq_ref[0, :, h * G:(h + 1) * G, :] = \
                dq_acc[r].reshape(bq, G, D).astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc,
                 *, scale, causal, block_q, block_k, num_q,
                 num_heads: int, groups: int, window=None, softcap=None):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    D = q_ref.shape[-1]
    bq = q_ref.shape[1]
    G = groups
    KV = num_heads // G
    ng = bq * G

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute(masked):
        if masked and (causal or window is not None):
            q_pos, k_pos = _positions((ng, block_k), block_q, block_k,
                                      qi, ki, G)
            kill = k_pos > q_pos if causal else jnp.zeros((ng, block_k), bool)
            if window is not None:
                kill = kill | (q_pos - k_pos >= window)
        for h in range(KV):
            q = q_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, D)
            do = do_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, D)
            lse = lse_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, 1)
            delta = delta_ref[0, :, h * G:(h + 1) * G, :].reshape(ng, 1)
            k = k_ref[0, :, h * D:(h + 1) * D]
            v = v_ref[0, :, h * D:(h + 1) * D]
            s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if softcap is not None:
                t = jnp.tanh(s / softcap)
                s = softcap * t
            if masked and (causal or window is not None):
                s = jnp.where(kill, NEG_INF, s)
            p = jnp.exp(s - lse)
            if masked:
                p = jnp.where(s <= NEG_INF, 0.0, p)
            c = slice(h * D, (h + 1) * D)  # this head's lane columns
            # dv += p^T @ do (sums the G query heads: GQA reduce); dk += ds^T @ q
            dv_acc[:, c] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            if softcap is not None:
                ds = ds * (1.0 - t * t)
            dk_acc[:, c] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)

    # dk/dv tile liveness mirrors the per-head kernel's kv-major view
    live = True
    interior = True
    if causal:
        live = qi * block_q + block_q - 1 >= ki * block_k
        interior = ki * block_k + block_k - 1 <= qi * block_q
    if window is not None:
        live = live & (qi * block_q
                       <= ki * block_k + block_k - 1 + (window - 1))
        interior = interior & (
            qi * block_q + block_q - 1 - ki * block_k <= window - 1)
    if live is True:
        _compute(masked=False)
    else:
        @pl.when(live & interior)
        def _():
            _compute(masked=False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _shapes(q, k, block_q, block_k):
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, (
        f"seq lens ({Sq},{Sk}) must divide blocks ({block_q},{block_k})")
    return B, Sq, H, D, Sk, KV, G, block_q, block_k


def flash_fwd_folded(q, k, v, scale, causal, block_q, block_k, interpret,
                     window=None, softcap=None):
    B, Sq, H, D, Sk, KV, G, block_q, block_k = _shapes(q, k, block_q, block_k)
    num_q, num_kv = Sq // block_q, Sk // block_k
    kf = k.reshape(B, Sk, KV * D)
    vf = v.reshape(B, Sk, KV * D)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kv=num_kv, num_heads=H, groups=G,
        window=window, softcap=softcap)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, H, D), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, block_k, KV * D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, KV * D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, H, D), lambda b, i, j: (b, i, 0, 0)),
            pl.BlockSpec((1, block_q, H, 1), lambda b, i, j: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype),
            jax.ShapeDtypeStruct((B, Sq, H, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((H * block_q, D), jnp.float32),
            pltpu.VMEM((H * block_q, 1), jnp.float32),
            pltpu.VMEM((H * block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, kf, vf)
    return out, lse


def flash_bwd_folded(q, k, v, lse, o, g_out, scale, causal, block_q, block_k,
                     interpret, window=None, softcap=None):
    B, Sq, H, D, Sk, KV, G, block_q, block_k = _shapes(q, k, block_q, block_k)
    num_q, num_kv = Sq // block_q, Sk // block_k
    kf = k.reshape(B, Sk, KV * D)
    vf = v.reshape(B, Sk, KV * D)
    delta = jnp.sum(g_out.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, Sq, H, 1]

    q_spec = pl.BlockSpec((1, block_q, H, D), lambda b, i, j: (b, i, 0, 0))
    k_spec = pl.BlockSpec((1, block_k, KV * D), lambda b, i, j: (b, j, 0))
    r_spec = pl.BlockSpec((1, block_q, H, 1), lambda b, i, j: (b, i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_kv=num_kv,
                          num_heads=H, groups=G, window=window,
                          softcap=softcap),
        grid=(B, num_q, num_kv),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((H * block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, kf, vf, g_out, lse, delta)

    q_spec2 = pl.BlockSpec((1, block_q, H, D), lambda b, j, i: (b, i, 0, 0))
    k_spec2 = pl.BlockSpec((1, block_k, KV * D), lambda b, j, i: (b, j, 0))
    r_spec2 = pl.BlockSpec((1, block_q, H, 1), lambda b, j, i: (b, i, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          num_heads=H, groups=G, window=window,
                          softcap=softcap),
        grid=(B, num_kv, num_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sk, KV * D), k.dtype),
            jax.ShapeDtypeStruct((B, Sk, KV * D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, KV * D), jnp.float32),
            pltpu.VMEM((block_k, KV * D), jnp.float32),
        ],
        interpret=interpret,
    )(q, kf, vf, g_out, lse, delta)
    return dq, dk.reshape(B, Sk, KV, D), dv.reshape(B, Sk, KV, D)


from .registry import registry  # noqa: E402

registry.register("flash_attention_folded", "pallas" if _HAS_PLTPU else "xla",
                  True, "head-folded flash variant (DS_TPU_FLASH_FOLDED=1): "
                  "all KV heads per grid step, natural [B,S,H,D] layouts")
