"""Grouped (megablocks-style) MoE matmul.

Reference capability: ``deepspeed/inference/v2/kernels/cutlass_ops/moe_gemm/``
plus the ``moe_scatter``/``moe_gather`` ragged ops — tokens are routed to
experts and each expert multiplies only its own tokens, so per-token FLOPs
scale with top-k instead of the expert count E (the round-1 path computed
every expert for every token and masked: E/k× wasted FLOPs).

TPU design: sort the (token, choice) assignments by expert id (one XLA sort),
run the three expert MLPs as ragged grouped GEMMs with
``jax.lax.ragged_dot`` — on TPU/GPU this lowers to the native
``chlo.ragged_dot`` grouped-GEMM instruction (MXU, FLOPs ∝ top-k; the CPU
backend decomposes to a dense-masked form, which only the test harness
sees), the grouped-GEMM analog of the reference's CUTLASS kernel — then
combine with a weighted scatter-add back to token order. Fully differentiable (ragged_dot carries transpose rules), static
shapes throughout (T*k assignments regardless of routing), no capacity
factor and no token dropping: exact token-choice semantics.
"""

import jax
import jax.numpy as jnp


def moe_sort_tokens(top_idx):
    """Sort (token, choice) assignments by expert.

    Args:
      top_idx: ``[T, k]`` int32 expert id per (token, choice).
    Returns:
      (tok_sorted ``[T*k]`` source token per sorted assignment,
       order ``[T*k]`` the sort permutation over flattened assignments,
       group_sizes ``[E?]`` — caller computes via bincount; returned here
       as the sorted expert ids for convenience).
    """
    Tk = top_idx.size
    flat_e = top_idx.reshape(-1)
    order = jnp.argsort(flat_e, stable=True)
    tok_sorted = (jnp.arange(Tk, dtype=jnp.int32) // top_idx.shape[1])[order]
    return tok_sorted, order, flat_e[order]


def moe_grouped_mlp(x, w1, w3, w2, top_idx, top_w, *, activation=jax.nn.silu):
    """Token-choice MoE MLP via grouped GEMMs.

    ``y[t] = Σ_j top_w[t,j] · ffn_{top_idx[t,j]}(x[t])`` with
    ``ffn_e(h) = (act(h @ w1[e]) * (h @ w3[e])) @ w2[e]`` (SwiGLU).

    Args:
      x: ``[T, H]`` tokens.
      w1, w3: ``[E, H, F]``; w2: ``[E, F, H]`` expert weights.
      top_idx: ``[T, k]`` int32 chosen experts.
      top_w: ``[T, k]`` combine weights (already normalized).
    Returns:
      ``[T, H]`` in x.dtype.
    """
    T, H = x.shape
    E = w1.shape[0]
    k = top_idx.shape[1]

    tok_sorted, order, _ = moe_sort_tokens(top_idx)
    group_sizes = jnp.bincount(top_idx.reshape(-1), length=E).astype(jnp.int32)

    xs = x[tok_sorted]  # [T*k, H] expert-contiguous
    h1 = jax.lax.ragged_dot(xs, w1, group_sizes,
                            preferred_element_type=jnp.float32).astype(x.dtype)
    h3 = jax.lax.ragged_dot(xs, w3, group_sizes,
                            preferred_element_type=jnp.float32).astype(x.dtype)
    act = activation(h1) * h3
    y = jax.lax.ragged_dot(act, w2, group_sizes,
                           preferred_element_type=jnp.float32)  # [T*k, H] fp32

    w_sorted = top_w.reshape(-1)[order].astype(jnp.float32)
    out = jnp.zeros((T, H), jnp.float32).at[tok_sorted].add(y * w_sorted[:, None])
    return out.astype(x.dtype)


def lora_sort_slots(slots, n_slots):
    """Sort per-token adapter slot ids for the grouped LoRA delta — the
    k=1 specialization of :func:`moe_sort_tokens` (every token has exactly
    one adapter). Hoist this ONCE per forward and reuse the (order,
    group_sizes) pair across every layer/target: the sort is a function of
    the batch's slot assignment only.

    Args:
      slots: ``[T]`` int32 adapter slot per token (0 = identity).
      n_slots: static slot-pool size (bank leading dim).
    Returns:
      (order ``[T]`` sort permutation, group_sizes ``[n_slots]`` int32).
    """
    order = jnp.argsort(slots, stable=True)
    group_sizes = jnp.bincount(slots, length=n_slots).astype(jnp.int32)
    return order, group_sizes


def lora_grouped_delta(x, a, b, scale_sorted, order, group_sizes):
    """Batched multi-LoRA delta ``y[t] += B[s_t] @ (A[s_t] @ x[t]) * scale``
    via the sort-by-slot ragged idiom — ONE pair of grouped GEMMs covers a
    mixed-adapter token wave, FLOPs ∝ rank regardless of how many adapters
    are live, and slot 0's zero factors make base-only tokens an exact
    no-op (delta ≡ 0.0, so streams stay bit-identical to the base model).

    Args:
      x: ``[T, in]`` tokens (original order).
      a: ``[n_slots, in, r]`` stacked down-projection factors.
      b: ``[n_slots, r, out]`` stacked up-projection factors.
      scale_sorted: ``[T]`` fp32 per-token ``alpha / sqrt(r)`` in SORTED
        order (``scale[slots][order]`` — the caller gathers once).
      order, group_sizes: from :func:`lora_sort_slots`.
    Returns:
      ``[T, out]`` fp32 delta in original token order.
    """
    xs = x[order]
    h = jax.lax.ragged_dot(xs, a, group_sizes,
                           preferred_element_type=jnp.float32).astype(x.dtype)
    y = jax.lax.ragged_dot(h, b, group_sizes,
                           preferred_element_type=jnp.float32)
    y = y * scale_sorted[:, None]
    return jnp.zeros((x.shape[0], b.shape[-1]), jnp.float32).at[order].set(y)


def lora_dense_delta(x, a, b, slots, scale):
    """Dense-gather reference for :func:`lora_grouped_delta` — the numerics
    oracle: per-token factor gather + two plain matmuls, no sort."""
    af = a[slots].astype(jnp.float32)        # [T, in, r]
    bf = b[slots].astype(jnp.float32)        # [T, r, out]
    h = jnp.einsum("ti,tir->tr", x.astype(jnp.float32), af)
    y = jnp.einsum("tr,tro->to", h, bf)
    return y * scale[slots][:, None]


def moe_dense_mlp(x, w1, w3, w2, top_idx, top_w, *, activation=jax.nn.silu):
    """Dense-over-experts reference (every expert for every token, masked
    combine) — the numerics oracle for tests and the fallback when an
    'expert'-sharded mesh axis makes the sort/a2a layout preferable."""
    E = w1.shape[0]
    cw = jnp.sum(top_w[..., None] * jax.nn.one_hot(top_idx, E, dtype=top_w.dtype),
                 axis=-2)  # [T, E]
    a = activation(jnp.einsum("th,ehf->tef", x, w1)) * jnp.einsum("th,ehf->tef", x, w3)
    y = jnp.einsum("tef,efh->teh", a, w2)
    return jnp.einsum("te,teh->th", cw.astype(y.dtype), y).astype(x.dtype)


from .registry import registry  # noqa: E402

registry.register("grouped_matmul", "xla", True,
                  "MoE grouped GEMM, FLOPs proportional to top-k (reference "
                  "cutlass_ops moe_gemm)")
