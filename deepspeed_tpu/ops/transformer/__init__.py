"""Legacy fused transformer layer API.

Reference: ``deepspeed/ops/transformer/transformer.py:296
DeepSpeedTransformerLayer`` + ``DeepSpeedTransformerConfig :21`` — the
BERT-era fused CUDA layer (``csrc/transformer/*.cu``, ~13k LoC of
hand-fused gelu/dropout/softmax/norm kernels). Under XLA the fusion is the
compiler's job: the layer here is the flax BERT encoder block
(``models/bert.py``), which jit compiles into the same fused form. The
config keeps the reference's field names so training scripts port.
"""

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Reference field names (transformer.py:21). Dropout ratios are
    accepted for compat; inference/eval path is deterministic (pass
    ``deterministic=False``-style rng plumbing at the flax level if dropout
    training is needed)."""
    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = False
    local_rank: int = -1

    def __post_init__(self):
        if self.intermediate_size == -1 and self.hidden_size > 0:
            self.intermediate_size = 4 * self.hidden_size


class DeepSpeedTransformerLayer:
    """One encoder layer with the reference's call shape:
    ``layer(hidden_states, attention_mask)`` → hidden states.

    Post-LN (the reference's default BERT ordering); ``pre_layer_norm`` is
    rejected explicitly rather than silently mis-ordered.
    """

    def __init__(self, config: DeepSpeedTransformerConfig, initial_params: Optional[Any] = None,
                 seed: int = 0):
        if config.pre_layer_norm:
            raise NotImplementedError(
                "pre_layer_norm=True: use models/llama.py (pre-LN decoder) or "
                "a flax encoder variant; this legacy shim is the post-LN BERT "
                "layer the reference kernels target")
        from ...models.bert import BertConfig, BertLayer
        import jax

        self.config = config
        self._cfg = BertConfig(
            vocab_size=1,  # unused at layer granularity
            hidden_size=config.hidden_size,
            intermediate_size=config.intermediate_size,
            num_hidden_layers=1,
            num_attention_heads=config.heads,
            layer_norm_eps=config.layer_norm_eps,
            dtype=jnp.float16 if config.fp16 else jnp.float32,
        )
        self._layer = BertLayer(self._cfg)
        if initial_params is None:
            x = jnp.zeros((1, 8, config.hidden_size), self._cfg.dtype)
            initial_params = self._layer.init(
                jax.random.PRNGKey(seed if config.seed < 0 else config.seed),
                x)["params"]
        self.params = initial_params
        self._fwd = jax.jit(lambda p, x, m: self._layer.apply({"params": p}, x, m))
        self._fwd_nomask = jax.jit(lambda p, x: self._layer.apply({"params": p}, x))

    def __call__(self, hidden_states, attention_mask=None):
        if attention_mask is None:
            return self._fwd_nomask(self.params, hidden_states)
        return self._fwd(self.params, hidden_states, attention_mask)

    forward = __call__
