"""Flash attention — Pallas TPU kernel with XLA fallback.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/inference/csrc/softmax.cu``, flash paths in
``deepspeed/inference/v2/kernels/ragged_ops/blocked_flash``): blocked
online-softmax attention that never materializes the [S, S] score matrix.

Grid layout: (batch*heads, q_blocks, kv_blocks) with the kv dim innermost —
accumulators (o, m, l) live in VMEM scratch that persists across the kv
iterations of one q block; output is finalized on the last kv step. Causal
masking prunes fully-masked kv blocks via `pl.when`.

Backward: `jax.custom_vjp` whose bwd recomputes attention with the XLA path
(flash-style remat — the standard memory/FLOPs trade); a dedicated Pallas
bwd kernel is a later optimization.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas

NEG_INF = -1e30


def _xla_attention(q, k, v, scale, causal):
    """Reference implementation, [B, S, H, D]; XLA fuses this reasonably."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        n, m = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((n, m), bool), k=m - n)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc, m_s, l_s, *, scale, causal,
                  block_q, block_k, num_kv):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)  # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        m_prev, l_prev = m_s[:, 0], l_s[:, 0]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1))
        m_safe = jnp.where(m_cur <= NEG_INF, 0.0, m_cur)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(s <= NEG_INF, 0.0, p)
        corr = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - m_safe))
        l_cur = l_prev * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(p, v, (((1, ), (0, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr[:, None] + pv
        m_s[:, 0] = m_cur
        l_s[:, 0] = l_cur

    if causal:
        # skip kv blocks entirely above the diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_s[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, (
        f"seq lens ({Sq},{Sk}) must be divisible by blocks ({block_q},{block_k})")
    num_q, num_kv = Sq // block_q, Sk // block_k

    # [B, S, H, D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, num_kv=num_kv)
    scratch = [
        pltpu.VMEM((block_q, D), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]

    out = pl.pallas_call(
        kernel,
        grid=(B * H, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret), (q, k, v)


def _bwd_rule(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, scale, causal), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q,
                    k,
                    v,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128,
                    block_k: int = 128,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Blocked attention over [B, S, H, D] tensors.

    Dispatches to the Pallas kernel on TPU (or with interpret=True anywhere);
    falls back to the fused XLA softmax-attention path otherwise.
    """
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if use_pallas(force_pallas) or interpret:
        return _flash_attention(q, k, v, scale, causal, block_q, block_k, interpret)
    return _xla_attention(q, k, v, scale, causal)


registry.register("flash_attention", "pallas" if _HAS_PLTPU else "xla", True)
