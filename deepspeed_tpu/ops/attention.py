"""Flash attention — Pallas TPU kernels (fwd AND bwd) with XLA fallback.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/inference/csrc/softmax.cu``, the fused training-kernel
attention in ``csrc/transformer/`` and the blocked flash paths in
``deepspeed/inference/v2/kernels/ragged_ops/blocked_flash``): blocked
online-softmax attention that never materializes the [S, S] score matrix —
in either direction.

Layout: GQA is native. Queries arrive ``[B, S, H, D]`` and K/V
``[B, S, KV, D]`` with ``H = KV * G``; tensors are regrouped to
``[B*KV, G, S, D]`` so one grid step contracts the ``G * block_q`` query
rows of a KV group against one K/V block — K/V are never expanded to query
heads (G× HBM saving), and the folded G dimension *fattens* the MXU matmul.

Forward (grid ``(B*KV, q_blocks, kv_blocks)``, kv innermost): accumulators
(o, m, l) persist in VMEM scratch across the kv sweep; the log-sum-exp is
written out as a residual. Backward is the standard two-pass recompute:
a dq kernel sweeps kv blocks per q block, a dk/dv kernel sweeps q blocks
per kv block; both rebuild p from the saved LSE (no second online softmax)
and skip fully-masked blocks under causal.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from .registry import registry, use_pallas

NEG_INF = -1e30
LSE_MASKED = 1e30  # rows that saw no key: exp(s - LSE_MASKED) == 0


def softcap_scores(s, cap):
    """Gemma-2 logit softcapping: cap * tanh(s / cap), applied AFTER the
    scale and BEFORE any mask/bias — the single definition every attention
    path (flash fwd/bwd kernels, paged kernel, XLA fallbacks, model dense
    branches) shares so kernel and reference numerics cannot drift."""
    return cap * jnp.tanh(s / cap)


def _xla_attention(q, k, v, scale, causal, window=None, softcap=None):
    """Reference implementation; q [B, S, H, D], k/v [B, S, KV, D] (GQA ok)."""
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, D)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32) * scale
    if softcap is not None:  # Gemma-2: cap BEFORE masking
        s = softcap * jnp.tanh(s / softcap)
    if causal or window is not None:
        n, m = q.shape[1], k.shape[1]
        mask = jnp.ones((n, m), bool)
        if causal:
            mask &= jnp.tril(mask, k=m - n)
        if window is not None:
            qpos = jnp.arange(n)[:, None] + (m - n)
            mask &= qpos - jnp.arange(m)[None, :] < window
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v)
    return out.reshape(B, Sq, H, D)


def _row_pos(shape, block_q, offset):
    """Absolute q position of each row in a [G*BQ, BK] score tile (rows are
    g-major: row = g * BQ + pos)."""
    r = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    return offset + r % block_q


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s,
                *, scale, causal, block_q, block_k, num_kv, window=None,
                softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    def _compute(masked):
        g, bq, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
        # matmul operands stay in the INPUT dtype (bf16 on the training
        # path): the MXU's fast path is bf16 x bf16 with fp32 accumulation
        # (preferred_element_type) — casting operands to fp32 first would
        # run every dot at the several-fold-slower fp32 rate. All softmax
        # arithmetic happens on the fp32 accumulator outputs.
        q = q_ref[0].reshape(g * bq, d)
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if softcap is not None:  # Gemma-2: cap BEFORE masking
            s = softcap_scores(s, softcap)
        if masked and (causal or window is not None):
            q_pos = _row_pos(s.shape, block_q, qi * block_q)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if causal:
                s = jnp.where(k_pos > q_pos, NEG_INF, s)
            if window is not None:  # local attention: drop keys out of window
                s = jnp.where(q_pos - k_pos >= window, NEG_INF, s)
        # Everything row-wise stays 2D [G*BQ, 1]: Mosaic cannot shape-cast a
        # lane-dim vector into a sublane column ((1,G,BQ)->(G*BQ,1) is an
        # "unsupported shape cast"), so no 1D intermediates are ever formed.
        m_prev, l_prev = m_s[:], l_s[:]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        m_safe = jnp.where(m_cur <= NEG_INF, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        if masked:
            # an INTERIOR block's scores are real numbers — only edge
            # blocks can carry NEG_INF rows that must zero out
            p = jnp.where(s <= NEG_INF, 0.0, p)
        corr = jnp.exp(jnp.where(m_prev <= NEG_INF, NEG_INF, m_prev - m_safe))
        l_cur = l_prev * corr + p.sum(axis=-1, keepdims=True)
        # p back to the input dtype for the MXU (standard flash practice —
        # GPU flash uses fp16/bf16 P too); the accumulator stays fp32
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[:] = acc[:] * corr + pv
        m_s[:] = m_cur
        l_s[:] = l_cur

    cond = True
    if causal:
        cond = ki * block_k <= qi * block_q + block_q - 1
    if window is not None:  # skip blocks entirely older than the window
        cond = cond & (ki * block_k + block_k - 1 >= qi * block_q - (window - 1))
    if not causal and window is None:
        if cond is True:
            _compute(masked=False)
        else:  # pragma: no cover — cond is always True without causal/window
            @pl.when(cond)
            def _():
                _compute(masked=False)
    else:
        # full/edge block specialization (splash-style): a block strictly
        # inside the causal/window region skips the iota+select mask chain
        # entirely — at seq >> block, most live blocks are interior, and
        # the 0801T1906 trace showed this elementwise work dominating the
        # kernel (70% of step time at ~6% of model FLOPs)
        interior = True
        if causal:
            interior = ki * block_k + block_k - 1 <= qi * block_q
        if window is not None:  # every (q, k) pair strictly inside window
            interior = interior & (
                qi * block_q + block_q - 1 - ki * block_k <= window - 1)

        @pl.when(cond & interior)
        def _():
            _compute(masked=False)

        @pl.when(cond & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        g, bq = o_ref.shape[1], o_ref.shape[2]
        l = l_s[:]  # [G*BQ, 1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).reshape(g, bq, -1).astype(o_ref.dtype)
        m_safe = jnp.where(m_s[:] <= NEG_INF, 0.0, m_s[:])
        lse = jnp.where(l == 0.0, LSE_MASKED, m_safe + jnp.log(safe_l))
        lse_ref[0] = lse.reshape(g, bq, 1)


def _regroup(q, k, v):
    """[B,S,H,D]/[B,S,KV,D] -> qg [B*KV, G, Sq, D], kt/vt [B*KV, Sk, D]."""
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = (q.reshape(B, Sq, KV, G, D).transpose(0, 2, 3, 1, 4)
          .reshape(B * KV, G, Sq, D))
    kt = k.transpose(0, 2, 1, 3).reshape(B * KV, k.shape[1], D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * KV, v.shape[1], D)
    return qg, kt, vt


def _use_folded() -> bool:
    """Legacy probe (kept for bench.py's journal tagging): whether the
    folded-variant *preference* is active — ``DS_TPU_FLASH_FOLDED`` env, or
    the deprecated ``.perf/FOLDED_PROVEN`` sentinel. Per-shape dispatch
    (ops/kernel_dispatch.py) now owns the actual folded-vs-per-head choice;
    this only reports the variant a Pallas leg falls back to when no
    measurement decides it."""
    from .kernel_dispatch import IMPL_FOLDED, _variant_preference
    return _variant_preference() == IMPL_FOLDED


def resolved_attention_variant() -> str:
    """The flash-attention variant that will ACTUALLY run on a Pallas leg —
    env override OR sentinel promotion resolved, not just the env var.
    Reporting surfaces (env_report, bench run tags) must use this: a
    sentinel-promoted run with the env unset is still a folded run, and
    labeling it per-head poisons any A/B that keys off the tag. For the
    full per-leg (fwd/bwd × impl × blocks) resolution use
    ``kernel_dispatch.resolved_note``."""
    return "folded" if _use_folded() else "per-head"


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret, window=None,
               softcap=None):
    """Per-head Pallas forward → (o, lse[B*KV, G, Sq, 1])."""
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    assert H % KV == 0, (H, KV)
    G = H // KV
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, (
        f"seq lens ({Sq},{Sk}) must be divisible by blocks ({block_q},{block_k})")
    num_q, num_kv = Sq // block_q, Sk // block_k

    qg, kt, vt = _regroup(q, k, v)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, num_kv=num_kv,
                               window=window, softcap=softcap)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * KV, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, i, j: (b, 0, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, G, block_q, D), lambda b, i, j: (b, 0, i, 0)),
            # trailing unit lane dim: every reshape of the LSE then keeps the
            # minormost dim intact (a supported Mosaic shape cast), unlike
            # (1,G,BQ)->(G*BQ,1) which fails to lower for G > 1
            pl.BlockSpec((1, G, block_q, 1), lambda b, i, j: (b, 0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * KV, G, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * KV, G, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((G * block_q, D), jnp.float32),
            pltpu.VMEM((G * block_q, 1), jnp.float32),
            pltpu.VMEM((G * block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qg, kt, vt)
    o = (out.reshape(B, KV, G, Sq, D).transpose(0, 3, 1, 2, 4)
         .reshape(B, Sq, H, D))
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
               *, scale, causal, block_q, block_k, num_kv, window=None,
               softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute(masked):
        g, bq, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
        # operands stay in the input dtype for the MXU fast path (see
        # _fwd_kernel); fp32 only on accumulator outputs + softmax math
        q = q_ref[0].reshape(g * bq, d)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].reshape(g * bq, d)
        # lse/delta carry a trailing unit lane dim so this reshape is a
        # supported Mosaic cast (minormost dim preserved); no 1D intermediates
        lse = lse_ref[0].reshape(g * bq, 1)
        delta = delta_ref[0].reshape(g * bq, 1)

        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if softcap is not None:
            t = jnp.tanh(s / softcap)
            s = softcap * t  # == softcap_scores; t reused for d/ds = 1 - t^2
        if masked and (causal or window is not None):
            q_pos = _row_pos(s.shape, block_q, qi * block_q)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if causal:
                s = jnp.where(k_pos > q_pos, NEG_INF, s)
            if window is not None:
                s = jnp.where(q_pos - k_pos >= window, NEG_INF, s)
        p = jnp.exp(s - lse)
        if masked:  # interior blocks never carry NEG_INF scores
            p = jnp.where(s <= NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        if softcap is not None:  # chain through d/ds cap*tanh(s/cap) = 1 - t^2
            ds = ds * (1.0 - t * t)
        dq_acc[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    cond = True
    if causal:
        cond = ki * block_k <= qi * block_q + block_q - 1
    if window is not None:
        cond = cond & (ki * block_k + block_k - 1 >= qi * block_q - (window - 1))
    if not causal and window is None:
        _compute(masked=False)
    else:
        # full/edge specialization — see _fwd_kernel
        interior = True
        if causal:
            interior = ki * block_k + block_k - 1 <= qi * block_q
        if window is not None:
            interior = interior & (
                qi * block_q + block_q - 1 - ki * block_k <= window - 1)

        @pl.when(cond & interior)
        def _():
            _compute(masked=False)

        @pl.when(cond & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        g, bq = dq_ref.shape[1], dq_ref.shape[2]
        dq_ref[0] = dq_acc[:].reshape(g, bq, -1).astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc,
                 *, scale, causal, block_q, block_k, num_q, window=None,
                 softcap=None):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute(masked):
        g, bq, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
        # operands stay in the input dtype for the MXU fast path (see
        # _fwd_kernel); fp32 only on accumulator outputs + softmax math
        q = q_ref[0].reshape(g * bq, d)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].reshape(g * bq, d)
        lse = lse_ref[0].reshape(g * bq, 1)
        delta = delta_ref[0].reshape(g * bq, 1)

        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if softcap is not None:
            t = jnp.tanh(s / softcap)
            s = softcap * t  # == softcap_scores; t reused for d/ds = 1 - t^2
        if masked and (causal or window is not None):
            q_pos = _row_pos(s.shape, block_q, qi * block_q)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if causal:
                s = jnp.where(k_pos > q_pos, NEG_INF, s)
            if window is not None:
                s = jnp.where(q_pos - k_pos >= window, NEG_INF, s)
        p = jnp.exp(s - lse)
        if masked:  # interior blocks never carry NEG_INF scores
            p = jnp.where(s <= NEG_INF, 0.0, p)
        # dv += pᵀ @ do ; dk += dsᵀ @ q — over the folded G*BQ rows, which
        # also sums the G query heads sharing this KV head (GQA reduce)
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        if softcap is not None:
            ds = ds * (1.0 - t * t)
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    cond = True
    if causal:
        # a q block contributes iff its last row can see this kv block
        cond = qi * block_q + block_q - 1 >= ki * block_k
    if window is not None:  # ...and its first row is not past the window
        cond = cond & (qi * block_q <= ki * block_k + block_k - 1 + (window - 1))
    if not causal and window is None:
        _compute(masked=False)
    else:
        # full/edge specialization — see _fwd_kernel. Interior here means
        # every (q, k) pair in the tile is unmasked: the whole q block is
        # at-or-after the kv block (causal) and inside the window
        interior = True
        if causal:
            interior = ki * block_k + block_k - 1 <= qi * block_q
        if window is not None:
            interior = interior & (
                qi * block_q + block_q - 1 - ki * block_k <= window - 1)

        @pl.when(cond & interior)
        def _():
            _compute(masked=False)

        @pl.when(cond & jnp.logical_not(interior))
        def _():
            _compute(masked=True)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(res, g_out, scale, causal, block_q, block_k, interpret, window=None,
               softcap=None):
    """Per-head Pallas backward; ``res`` carries lse in the per-head
    [B*KV, G, Sq, 1] layout."""
    q, k, v, o, lse = res
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    num_q, num_kv = Sq // block_q, Sk // block_k

    qg, kt, vt = _regroup(q, k, v)
    dog, _, _ = _regroup(g_out, k, v)
    og, _, _ = _regroup(o, k, v)
    delta = jnp.sum(dog.astype(jnp.float32) * og.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [B*KV, G, Sq, 1] — unit lane dim, see lse

    q_spec = pl.BlockSpec((1, G, block_q, D), lambda b, i, j: (b, 0, i, 0))
    k_spec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    r_spec = pl.BlockSpec((1, G, block_q, 1), lambda b, i, j: (b, 0, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_kv=num_kv,
                          window=window, softcap=softcap),
        grid=(B * KV, num_q, num_kv),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=pl.BlockSpec((1, G, block_q, D), lambda b, i, j: (b, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * KV, G, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((G * block_q, D), jnp.float32)],
        interpret=interpret,
    )(qg, kt, vt, dog, lse, delta)

    # kv-major grid for dk/dv: q sweep innermost
    q_spec2 = pl.BlockSpec((1, G, block_q, D), lambda b, j, i: (b, 0, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    r_spec2 = pl.BlockSpec((1, G, block_q, 1), lambda b, j, i: (b, 0, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          window=window, softcap=softcap),
        grid=(B * KV, num_kv, num_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * KV, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * KV, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(qg, kt, vt, dog, lse, delta)

    dq = (dq.reshape(B, KV, G, Sq, D).transpose(0, 3, 1, 2, 4)
          .reshape(B, Sq, H, D))
    dk = dk.reshape(B, KV, Sk, D).transpose(0, 2, 1, 3)
    dv = dv.reshape(B, KV, Sk, D).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# shape-aware dispatch (ops/kernel_dispatch.py decides; this wires the legs)
# ---------------------------------------------------------------------------


def _xla_attention_lse(q, k, v, scale, causal, window=None, softcap=None):
    """XLA forward that ALSO returns the log-sum-exp residual, so a Pallas
    backward can pair with an XLA forward (the 42.7 ms < 62.9 ms dispatch
    at hd64/seq1024). Scores accumulate in fp32 (preferred_element_type)
    so the LSE matches what the Pallas bwd kernels recompute in-kernel;
    lse comes back in the NATURAL [B, Sq, H, 1] layout."""
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, D)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                   preferred_element_type=jnp.float32) * scale
    if softcap is not None:  # Gemma-2: cap BEFORE masking
        s = softcap_scores(s, softcap)
    if causal or window is not None:
        n, m = q.shape[1], k.shape[1]
        mask = jnp.ones((n, m), bool)
        if causal:
            mask &= jnp.tril(mask, k=m - n)
        if window is not None:
            qpos = jnp.arange(n)[:, None] + (m - n)
            mask &= qpos - jnp.arange(m)[None, :] < window
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    live = s > NEG_INF
    m_row = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m_row <= NEG_INF, 0.0, m_row)
    p = jnp.where(live, jnp.exp(s - m_safe), 0.0)
    l_row = p.sum(axis=-1, keepdims=True)
    safe_l = jnp.where(l_row == 0.0, 1.0, l_row)
    out = jnp.einsum("bkgqs,bskd->bqkgd", (p / safe_l).astype(v.dtype), v)
    lse = jnp.where(l_row == 0.0, LSE_MASKED, m_safe + jnp.log(safe_l))
    # [B, KV, G, Sq, 1] -> natural [B, Sq, H, 1]
    lse = lse.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, 1)
    return out.reshape(B, Sq, H, D), lse


def _lse_natural_to_perhead(lse, B, Sq, KV, G):
    """[B, Sq, H, 1] -> [B*KV, G, Sq, 1] (the per-head kernels' layout)."""
    return (lse.reshape(B, Sq, KV, G, 1).transpose(0, 2, 3, 1, 4)
            .reshape(B * KV, G, Sq, 1))


def _lse_perhead_to_natural(lse, B, Sq, KV, G):
    """[B*KV, G, Sq, 1] -> [B, Sq, H, 1]."""
    return (lse.reshape(B, KV, G, Sq, 1).transpose(0, 3, 1, 2, 4)
            .reshape(B, Sq, KV * G, 1))


def _fit_blocks(dec, Sq, Sk):
    """Clamp a Decision's blocks to divide the actual sequence lengths.

    Fit = largest power-of-two divisor of S that is <= the requested block
    (every eligible s % 128 == 0 shape reaches 128; an odd override can't
    silently degrade to block 1 — a degenerate fit keeps the requested
    block so the kernels' divisibility assert fails LOUDLY instead of
    silently running 1-wide blocks)."""

    def _fit(S, b):
        b = min(b, S)
        if S % b == 0:
            return b
        p = 1
        while p * 2 <= b and S % (p * 2) == 0:
            p *= 2
        return p if p >= 32 else b

    return dec._replace(block_q=_fit(Sq, dec.block_q),
                        block_k=_fit(Sk, dec.block_k))


def _run_fwd(q, k, v, scale, causal, window, softcap, interpret, dec,
             lse_layout):
    """Execute one forward leg per its Decision; returns (o, lse) with lse
    in ``lse_layout`` ("perhead" | "natural"), or lse=None when the paired
    backward doesn't need it (lse_layout=None)."""
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    if dec.impl == "xla":
        if lse_layout is None:
            return _xla_attention(q, k, v, scale, causal, window, softcap), None
        o, lse = _xla_attention_lse(q, k, v, scale, causal, window, softcap)
    elif dec.impl == "folded":
        from .attention_folded import flash_fwd_folded
        o, lse = flash_fwd_folded(q, k, v, scale, causal, dec.block_q,
                                  dec.block_k, interpret, window, softcap)
        # folded lse is already natural [B, Sq, H, 1]
    else:
        o, lse_ph = _flash_fwd(q, k, v, scale, causal, dec.block_q,
                               dec.block_k, interpret, window, softcap)
        if lse_layout == "perhead":
            return o, lse_ph
        lse = (None if lse_layout is None
               else _lse_perhead_to_natural(lse_ph, B, Sq, KV, G))
        return o, lse
    if lse_layout is None:
        return o, None
    if lse_layout == "perhead":
        lse = _lse_natural_to_perhead(lse, B, Sq, KV, G)
    return o, lse


def _bwd_lse_layout(bwd_dec):
    """Which lse layout the bwd leg consumes (None: no residual needed)."""
    return {"xla": None, "folded": "natural", "pallas": "perhead"}[bwd_dec.impl]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _dispatched_attention(q, k, v, scale, causal, window, softcap, interpret,
                          fwd_dec, bwd_dec):
    """Attention with INDEPENDENT per-leg kernel selection: ``fwd_dec`` and
    ``bwd_dec`` are hashable ``kernel_dispatch.Decision`` tuples resolved
    at trace time from the measured autotune cache / heuristic table —
    e.g. XLA fused fwd + Pallas flash bwd where XLA wins the forward."""
    o, _ = _run_fwd(q, k, v, scale, causal, window, softcap, interpret,
                    fwd_dec, None)
    return o


def _fwd_rule(q, k, v, scale, causal, window, softcap, interpret, fwd_dec,
              bwd_dec):
    o, lse = _run_fwd(q, k, v, scale, causal, window, softcap, interpret,
                      fwd_dec, _bwd_lse_layout(bwd_dec))
    return o, (q, k, v, o, lse)


def _bwd_rule(scale, causal, window, softcap, interpret, fwd_dec, bwd_dec,
              res, g):
    q, k, v, o, lse = res
    if bwd_dec.impl == "xla":
        # standard recompute: differentiate the XLA reference directly (no
        # LSE residual needed); used where the materialized-scores bwd wins
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_attention(q_, k_, v_, scale, causal,
                                              window, softcap), q, k, v)
        return vjp(g)
    if bwd_dec.impl == "folded":
        from .attention_folded import flash_bwd_folded
        return flash_bwd_folded(q, k, v, lse, o, g, scale, causal,
                                bwd_dec.block_q, bwd_dec.block_k, interpret,
                                window, softcap)
    return _flash_bwd((q, k, v, o, lse), g, scale, causal, bwd_dec.block_q,
                      bwd_dec.block_k, interpret, window, softcap)


_dispatched_attention.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q,
                    k,
                    v,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    window: Optional[int] = None,
                    softcap: Optional[float] = None,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False,
                    impl_fwd: Optional[str] = None,
                    impl_bwd: Optional[str] = None):
    """Blocked attention; q [B, S, H, D], k/v [B, S, KV, D] (GQA native).

    On TPU (or with interpret=True anywhere) the forward and backward
    implementations are selected INDEPENDENTLY per shape by
    ``ops/kernel_dispatch.py``: measured autotune-cache entries win, then
    the built-in heuristic table (XLA fused fwd + Pallas flash bwd at
    hd64/seq>=1024 — the round-5 chip measurement). ``impl_fwd``/
    ``impl_bwd`` ("xla" | "pallas" | "folded") pin a leg explicitly (tests,
    the sweep tool); ``block_q``/``block_k`` pin the Pallas tile sizes.
    Off-TPU without interpret, the pure-XLA fused path runs both legs.
    """
    from . import kernel_dispatch as kd

    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if not (use_pallas(force_pallas) or interpret):
        return _xla_attention(q, k, v, scale, causal, window, softcap)
    sig = kd.make_sig(q.shape, k.shape[2], k.shape[1], q.dtype, causal,
                      window, softcap)
    blocks = ((block_q, block_k)
              if block_q is not None and block_k is not None else None)
    fwd_dec, bwd_dec = kd.resolve(
        sig, "interpret" if interpret and not use_pallas(force_pallas)
        else None,
        impl_fwd=impl_fwd, impl_bwd=impl_bwd, blocks=blocks,
        pallas_only=bool(force_pallas) and impl_fwd is None
        and impl_bwd is None)
    fwd_dec = _fit_blocks(fwd_dec, q.shape[1], k.shape[1])
    bwd_dec = _fit_blocks(bwd_dec, q.shape[1], k.shape[1])
    return _dispatched_attention(q, k, v, scale, causal, window, softcap,
                                 interpret, fwd_dec, bwd_dec)


registry.register("flash_attention", "pallas" if _HAS_PLTPU else "xla", True)
