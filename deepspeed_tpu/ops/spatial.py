"""Spatial (diffusion) ops — NHWC bias-add family.

Reference: ``csrc/spatial/csrc/pt_binding.cpp:109-111`` exposes
``nhwc_bias_add`` / ``nhwc_bias_add_add`` / ``nhwc_bias_add_bias_add`` as
hand-vectorized CUDA kernels for diffusers UNet inference (the win there is
fusing the bias broadcast into one memory pass). Under XLA these are single
fused elementwise HLOs already — the functions exist for API parity and to
pin the channels-last (NHWC) broadcast semantics the reference kernels
implement (bias is per-channel, length C, added along the last axis).
"""

import jax
import jax.numpy as jnp

from .registry import registry


def _check_bias(x: jax.Array, bias: jax.Array) -> None:
    if bias.ndim != 1 or bias.shape[0] != x.shape[-1]:
        raise ValueError(f"bias must be [C={x.shape[-1]}] for NHWC input, "
                         f"got {bias.shape}")


def nhwc_bias_add(activation: jax.Array, bias: jax.Array) -> jax.Array:
    """activation [N, H, W, C] (or any [..., C]) + per-channel bias [C]."""
    _check_bias(activation, bias)
    return activation + bias.astype(activation.dtype)


def nhwc_bias_add_add(activation: jax.Array, bias: jax.Array,
                      other: jax.Array) -> jax.Array:
    """(activation + bias) + other — residual add fused with the bias pass."""
    _check_bias(activation, bias)
    return activation + bias.astype(activation.dtype) + other


def nhwc_bias_add_bias_add(activation: jax.Array, bias: jax.Array,
                           other: jax.Array, other_bias: jax.Array) -> jax.Array:
    """(activation + bias) + (other + other_bias) — two biased streams summed."""
    _check_bias(activation, bias)
    _check_bias(other, other_bias)
    return (activation + bias.astype(activation.dtype)
            + other + other_bias.astype(other.dtype))


registry.register("spatial", "xla", True)
