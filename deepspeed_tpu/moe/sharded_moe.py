"""GShard gating math (top-1 / top-2 / top-k) with capacity and aux losses.

Rebuild of reference ``deepspeed/moe/sharded_moe.py`` (``top1gating :183``,
``top2gating :290``, ``topkgating :374``, ``_capacity :161``) with the same
return contract:

    (l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C], exp_counts [E])

XLA-native differences:
- capacity is a *static* Python int (shapes are known at trace time); the
  reference's ``drop_tokens=False`` path (dynamic capacity = max live count,
  all-reduced over EP) is realized by padding capacity to S — no token is
  ever dropped, at the cost of a full-size buffer, which is the only
  static-shape-true version of "never drop".
- randomness (RSample noisy gating, Random Token Selection) takes an explicit
  `rng` key instead of global generator state.
"""

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Reference ``sharded_moe.py:161``: ceil(S/E * cf), floored at
    min_capacity — static ints under jit."""
    capacity = math.ceil((num_tokens / num_experts) * capacity_factor)
    return max(capacity, min_capacity)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def _gumbel(rng, shape):
    return jax.random.gumbel(rng, shape, jnp.float32)


def top1gating(logits: Array,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               used_token: Optional[Array] = None,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               use_rts: bool = True,
               rng: Optional[Array] = None) -> Tuple[Array, Array, Array, Array]:
    """Top-1 gating (reference ``sharded_moe.py:183``). logits: [S, E]."""
    S, E = logits.shape
    logits = logits.astype(jnp.float32)
    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample noisy gating needs an rng key"
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits + _gumbel(sub, logits.shape)
    gates = jax.nn.softmax(logits, axis=1)

    capacity = _capacity(S, E, capacity_factor, min_capacity) if drop_tokens else S

    indices1_s = jnp.argmax(logits_w_noise if noisy_gate_policy == "RSample" else gates, axis=1)
    mask1 = _one_hot(indices1_s, E)
    if used_token is not None:
        mask1 = used_token[:, None] * mask1

    exp_counts = jax.lax.stop_gradient(mask1.sum(axis=0))

    # load-balancing loss (GShard eq. 4): E * sum_e mean(gate_e) * mean(assigned_e)
    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = jnp.sum(me * jax.lax.stop_gradient(ce)) * E

    # Random Token Selection (reference :236): prioritize random tokens,
    # not sequence order, when over capacity
    if use_rts:
        assert rng is not None, "use_rts needs an rng key (or pass use_rts=False)"
        rng, sub = jax.random.split(rng)
        mask1_rand = mask1 * jax.random.uniform(sub, mask1.shape)
    else:
        mask1_rand = mask1

    assert S >= min_capacity, (
        "No. of tokens (batch-size) should be greater than min_capacity. "
        "Either set min_capacity to 0 or increase your batch size.")

    if capacity < S:
        # keep only the top-capacity tokens per expert column
        _, top_idx = jax.lax.top_k(mask1_rand.T, capacity)  # [E, C]
        keep = jnp.zeros((E, S), jnp.float32).at[jnp.arange(E)[:, None], top_idx].set(1.0).T
        mask1 = mask1 * keep

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)

    gates = gates * mask1
    locations1_sc = _one_hot(locations1_s, capacity)
    combine_weights = jnp.einsum("se,sc->sec", gates, locations1_sc)
    dispatch_mask = combine_weights.astype(bool)
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2gating(logits: Array,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               top2_2nd_expert_sampling: bool = True,
               rng: Optional[Array] = None) -> Tuple[Array, Array, Array, Array]:
    """Top-2 gating (reference ``sharded_moe.py:290``). logits: [S, E]."""
    S, E = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=1)

    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1_s, E)

    if top2_2nd_expert_sampling:
        assert rng is not None, "top2 2nd-expert sampling needs an rng key"
        rng, sub = jax.random.split(rng)
        logits = logits + _gumbel(sub, logits.shape)

    logits_except1 = jnp.where(mask1.astype(bool), -jnp.inf, logits)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2_s, E)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1
    locations2 = locations2 + mask1.sum(axis=0, keepdims=True)

    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = jnp.mean(me * jax.lax.stop_gradient(ce)) * E * E

    exp_counts = jax.lax.stop_gradient((mask1 + mask2).sum(axis=0))

    if drop_tokens:
        capacity = _capacity(S, E, capacity_factor * 2, min_capacity)
        mask1 = mask1 * (locations1 < capacity)
        mask2 = mask2 * (locations2 < capacity)
    else:
        capacity = 2 * S  # static "never drop": both assignments always fit

    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    locations2_s = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1_s = jnp.einsum("se,se->s", gates, mask1)
    gates2_s = jnp.einsum("se,se->s", gates, mask2)
    denom_s = jnp.clip(gates1_s + gates2_s, jnp.finfo(gates.dtype).eps, None)
    gates1_s = gates1_s / denom_s
    gates2_s = gates2_s / denom_s

    gates1 = gates1_s[:, None] * mask1
    gates2 = gates2_s[:, None] * mask2
    locations1_sc = _one_hot(locations1_s, capacity)
    locations2_sc = _one_hot(locations2_s, capacity)
    combine_weights = (jnp.einsum("se,sc->sec", gates1, locations1_sc) +
                       jnp.einsum("se,sc->sec", gates2, locations2_sc))
    dispatch_mask = combine_weights.astype(bool)
    return l_aux, combine_weights, dispatch_mask, exp_counts


def topkgating(logits: Array,
               k: int,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               drop_policy: str = "probs") -> Tuple[Array, Array, Array, Array]:
    """Top-k gating (reference ``sharded_moe.py:374``). logits: [S, E]."""
    S, E = logits.shape
    logits = logits.astype(jnp.float32)
    top_gate, top_idx = jax.lax.top_k(logits, k)  # [S, k]
    gates = jax.nn.softmax(logits, axis=1)

    mask = jnp.zeros((S, E), jnp.float32).at[jnp.arange(S)[:, None], top_idx].set(1.0)
    topk_masked_gates = jnp.zeros((S, E), jnp.float32).at[jnp.arange(S)[:, None],
                                                          top_idx].set(top_gate)

    exp_counts = jax.lax.stop_gradient(mask.sum(axis=0))

    me = gates.mean(axis=0)
    ce = mask.mean(axis=0)
    l_aux = jnp.mean(me * jax.lax.stop_gradient(ce)) * E * E / k

    if drop_tokens:
        capacity = _capacity(S, E, capacity_factor * k, min_capacity)
        if drop_policy == "probs":
            # keep the capacity highest-prob tokens per expert
            _, cap_idx = jax.lax.top_k(topk_masked_gates.T, min(capacity, S))  # [E, C]
            keep = jnp.zeros((E, S), jnp.float32).at[jnp.arange(E)[:, None], cap_idx].set(1.0).T
            mask = mask * keep
            locations = jnp.cumsum(mask, axis=0) - 1
        elif drop_policy == "position":
            locations = jnp.cumsum(mask, axis=0) - 1
            mask = mask * (locations < capacity)
        else:
            raise ValueError(f"Invalid drop_policy: {drop_policy}")
    else:
        capacity = S
        locations = jnp.cumsum(mask, axis=0) - 1

    gates_masked = gates * mask
    gates_s = gates_masked.sum(axis=-1, keepdims=True)
    denom_s = jnp.clip(gates_s, jnp.finfo(gates_masked.dtype).eps, None)
    gates_masked = gates_masked / denom_s

    locations_sc = _one_hot((locations * mask).astype(jnp.int32), capacity)
    combine_weights = jnp.einsum("se,sec->sec", gates_masked, locations_sc)
    # a token not assigned to expert e has mask[s,e]=0 -> gates_masked 0 -> no
    # contribution, but one_hot(0) would alias capacity slot 0; mask it out
    combine_weights = combine_weights * mask[..., None]
    dispatch_mask = combine_weights.astype(bool)
    return l_aux, combine_weights, dispatch_mask, exp_counts


try:
    import flax.linen as nn

    class TopKGate(nn.Module):
        """Gate module (reference ``sharded_moe.py:449 TopKGate``): a linear
        router over fp32 + one of the gating functions above."""
        model_dim: int
        num_experts: int
        k: int = 1
        capacity_factor: float = 1.0
        eval_capacity_factor: float = 1.0
        min_capacity: int = 4
        noisy_gate_policy: Optional[str] = None
        drop_tokens: bool = True
        use_rts: bool = True
        top2_2nd_expert_sampling: bool = True

        @nn.compact
        def __call__(self, x, used_token=None, train: bool = True):
            # router in fp32 always (reference TopKGate.forward casts to float)
            wg = nn.Dense(self.num_experts, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="wg")
            logits = wg(x.astype(jnp.float32))
            cf = self.capacity_factor if train else self.eval_capacity_factor
            needs_rng = (self.noisy_gate_policy == "RSample" and train) or \
                (self.k == 1 and self.use_rts) or (self.k == 2 and self.top2_2nd_expert_sampling)
            rng = self.make_rng("gating") if needs_rng and self.has_rng("gating") else None
            if self.k == 1:
                return top1gating(logits, cf, self.min_capacity, used_token,
                                  self.noisy_gate_policy if train else None,
                                  self.drop_tokens, self.use_rts and rng is not None, rng=rng)
            elif self.k == 2:
                return top2gating(logits, cf, self.min_capacity, self.drop_tokens,
                                  self.top2_2nd_expert_sampling and rng is not None, rng=rng)
            return topkgating(logits, self.k, cf, self.min_capacity, self.drop_tokens)

except ImportError:  # pragma: no cover
    TopKGate = None
