"""MoE parameter utilities.

Rebuild of reference ``deepspeed/moe/utils.py``: identify expert parameters
(``is_moe_param :27``) and split optimizer param groups so expert params get
their own group with expert-parallel reduction semantics
(``split_params_into_different_moe_groups_for_optimizer :72``).

Here params are pytrees, not nn.Parameters with attributes: an "MoE param" is
any leaf whose tree path contains an expert-stack marker (`experts` /
`deepspeed_moe` / `expert`). The engine uses the mask to (a) shard expert
leaves over the ``expert`` axis first and (b) skip the data-parallel grad
average over the expert axis for them.
"""

from typing import Any, Dict, List, Tuple

import jax

MOE_PATH_MARKERS = ("experts", "deepspeed_moe", "expert")


def _path_names(path) -> List[str]:
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "idx"):
            names.append(str(k.idx))
        else:
            names.append(str(k))
    return names


def is_moe_param_path(path) -> bool:
    return any(m in _path_names(path) for m in MOE_PATH_MARKERS)


def is_moe_param(tree: Any) -> Any:
    """Boolean mask pytree: True for expert leaves (reference utils.py:27)."""
    return jax.tree_util.tree_map_with_path(lambda p, _: is_moe_param_path(p), tree)


def split_params_into_different_moe_groups_for_optimizer(
        param_groups: Any) -> Tuple[Any, Any]:
    """Split a params pytree into (non_moe, moe) subtrees, with None in the
    complementary positions (reference utils.py:72 returns separate optimizer
    groups; optax analog: use these masks with optax.masked)."""
    non_moe = jax.tree_util.tree_map_with_path(
        lambda p, x: None if is_moe_param_path(p) else x, param_groups)
    moe = jax.tree_util.tree_map_with_path(
        lambda p, x: x if is_moe_param_path(p) else None, param_groups)
    return non_moe, moe
