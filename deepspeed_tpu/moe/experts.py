"""Expert modules.

Rebuild of reference ``deepspeed/moe/experts.py:13 Experts`` (a ModuleList of
deep-copied expert modules, each fed its [c, m] slice). TPU-native: experts
are a *stacked* parameter tree [E, ...] produced by ``nn.vmap`` — one einsum
per layer over all local experts (the grouped-GEMM formulation the reference
needs CUTLASS ``moe_gemm`` kernels for falls out of XLA batching), and the
leading expert dim is what the ``expert`` mesh axis shards.
"""

from typing import Callable, Optional

import jax.numpy as jnp
import flax.linen as nn

EXPERT_PARTITION_NAME = "expert"


class ExpertMLP(nn.Module):
    """A single FFN expert (what the reference users pass as `expert`)."""
    hidden_size: int
    intermediate_size: int
    activation: Callable = nn.gelu
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.intermediate_size, dtype=self.dtype, name="wi")(x)
        h = self.activation(h)
        return nn.Dense(self.hidden_size, dtype=self.dtype, name="wo")(h)


class Experts(nn.Module):
    """Vectorize an expert module over the expert dim: input [E, C, M] ->
    output [E, C, M], params stacked with leading dim E.

    `expert_fn` builds one expert template; it is constructed *inside* this
    module's scope so the stacked params nest under `experts/...` (flax binds
    submodules to the scope active at construction time).
    """
    expert_fn: Callable[[], nn.Module]
    num_experts: int

    @nn.compact
    def __call__(self, x):
        expert = self.expert_fn()
        vmapped = nn.vmap(
            lambda mdl, xi: mdl(xi),
            in_axes=0,
            out_axes=0,
            axis_size=self.num_experts,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: EXPERT_PARTITION_NAME},
        )
        return vmapped(expert, x)
