"""MoE layer + wrapper module.

Rebuild of reference ``deepspeed/moe/layer.py:17 MoE`` and
``sharded_moe.py:533 MOELayer``:

    gate -> dispatch einsum("sec,sm->ecm") -> [all-to-all over EP]
         -> experts -> [all-to-all back] -> combine einsum("sec,ecm->sm")

The reference's explicit ``_AllToAll`` autograd function (:96) is replaced by
``with_sharding_constraint``: tokens enter sharded over the data axes, the
dispatched [E, C, M] tensor is constrained to shard E over the ``expert``
mesh axis, and XLA lowers the resharding to the same ICI all-to-all — in both
directions, with autodiff giving the transposed collective in backward.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_mesh_context, mesh_is_initialized
from .experts import Experts, ExpertMLP
from .sharded_moe import TopKGate


class MOELayer(nn.Module):
    """Core dispatch/combine (reference ``sharded_moe.py:533``).

    Builds its own gate + experts children (so params nest under this
    module's name, matching the reference's `deepspeed_moe` state-dict
    prefix).
    """
    model_dim: int
    num_experts: int
    expert_fn: Callable[[], nn.Module]
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    top2_2nd_expert_sampling: bool = True
    use_sharding_constraint: bool = True

    @nn.compact
    def __call__(self, x, used_token=None, train: bool = True):
        gate = TopKGate(model_dim=self.model_dim,
                        num_experts=self.num_experts,
                        k=self.k,
                        capacity_factor=self.capacity_factor,
                        eval_capacity_factor=self.eval_capacity_factor,
                        min_capacity=self.min_capacity,
                        noisy_gate_policy=self.noisy_gate_policy,
                        drop_tokens=self.drop_tokens,
                        use_rts=self.use_rts,
                        top2_2nd_expert_sampling=self.top2_2nd_expert_sampling,
                        name="gate")
        experts = Experts(expert_fn=self.expert_fn, num_experts=self.num_experts,
                          name="experts")

        orig_shape = x.shape
        d_model = orig_shape[-1]
        reshaped = x.reshape(-1, d_model)  # [S, M] tokens

        l_aux, combine_weights, dispatch_mask, exp_counts = gate(reshaped, used_token,
                                                                 train=train)

        dispatched = jnp.einsum("sec,sm->ecm", dispatch_mask.astype(x.dtype), reshaped)
        dispatched = self._constrain_expert(dispatched)
        expert_out = experts(dispatched)  # [E, C, M]
        expert_out = self._constrain_expert(expert_out)
        combined = jnp.einsum("sec,ecm->sm", combine_weights.astype(x.dtype), expert_out)
        return combined.reshape(orig_shape), l_aux, exp_counts

    def _constrain_expert(self, t):
        if not self.use_sharding_constraint or not mesh_is_initialized():
            return t
        ctx = get_mesh_context()
        if ctx.axis_size("expert") <= 1:
            return t
        return jax.lax.with_sharding_constraint(t, ctx.sharding("expert", None, None))


class MoE(nn.Module):
    """User-facing wrapper (reference ``moe/layer.py:17``): returns
    (output, l_aux, exp_counts). `expert` defaults to an FFN sized by
    `hidden_size`/`intermediate_size` when not given; pass `expert_fn` for a
    custom expert architecture (a factory, so each instantiation lands in the
    experts scope)."""
    hidden_size: int
    num_experts: int = 1
    ep_size: int = 1  # informational; sharding comes from the mesh
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    intermediate_size: Optional[int] = None
    expert_fn: Optional[Callable[[], nn.Module]] = None
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    top2_2nd_expert_sampling: bool = True

    @nn.compact
    def __call__(self, hidden_states, used_token=None, train: bool = True):
        expert_fn = self.expert_fn
        if expert_fn is None:
            hidden, inter = self.hidden_size, self.intermediate_size or 4 * self.hidden_size
            dtype = hidden_states.dtype
            expert_fn = lambda: ExpertMLP(hidden_size=hidden, intermediate_size=inter,
                                          dtype=dtype)
        layer = MOELayer(model_dim=self.hidden_size,
                         num_experts=self.num_experts,
                         expert_fn=expert_fn,
                         k=self.k,
                         capacity_factor=self.capacity_factor,
                         eval_capacity_factor=self.eval_capacity_factor,
                         min_capacity=self.min_capacity,
                         noisy_gate_policy=self.noisy_gate_policy,
                         drop_tokens=self.drop_tokens,
                         use_rts=self.use_rts,
                         top2_2nd_expert_sampling=self.top2_2nd_expert_sampling,
                         name="deepspeed_moe")
        return layer(hidden_states, used_token, train=train)
