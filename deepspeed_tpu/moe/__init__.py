"""Mixture-of-Experts with expert parallelism.

TPU-native rebuild of reference ``deepspeed/moe/``: GShard-style top-k gating
with capacity + load-balancing losses (``sharded_moe.py``), expert-parallel
dispatch over the ``expert`` mesh axis (the reference's ``_AllToAll :96`` is
here a sharding constraint XLA lowers to an ICI all-to-all), and the `MoE`
module wrapper (``layer.py:17``).
"""

from .sharded_moe import top1gating, top2gating, topkgating, TopKGate
from .experts import Experts, ExpertMLP
from .layer import MoE, MOELayer
from .utils import is_moe_param, split_params_into_different_moe_groups_for_optimizer

__all__ = [
    "top1gating", "top2gating", "topkgating", "TopKGate",
    "Experts", "ExpertMLP", "MoE", "MOELayer",
    "is_moe_param", "split_params_into_different_moe_groups_for_optimizer",
]
