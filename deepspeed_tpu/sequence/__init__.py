"""Sequence parallelism (DeepSpeed-Ulysses) + ring attention.

TPU-native rebuild of reference ``deepspeed/sequence/`` plus the ring-attention
context-parallel extension the reference lacks (SURVEY.md §2.4: flagged as the
TPU CP analog).
"""

from .layer import (DistributedAttention, seq_all_to_all, ulysses_spmd,
                    ulysses_flash)
from .ring import ring_attention
from .cross_entropy import vocab_sequence_parallel_cross_entropy

__all__ = [
    "DistributedAttention",
    "seq_all_to_all",
    "ulysses_spmd",
    "ulysses_flash",
    "ring_attention",
    "vocab_sequence_parallel_cross_entropy",
]
