"""DeepSpeed-Ulysses sequence parallelism, TPU-native.

Rebuild of reference ``deepspeed/sequence/layer.py`` (``_SeqAllToAll :90``,
``single_all_to_all :41``, ``DistributedAttention :145``): shard the sequence
dim across the ``seq`` mesh axis; before attention, all-to-all swaps the
sharding from [b, s/P, h, d] to [b, s, h/P, d] so each device holds full
sequences for a head subset; after attention the inverse all-to-all restores
sequence sharding.

Two implementations, matching the two JAX programming styles:

1. `seq_all_to_all` / `DistributedAttention` — explicit ``lax.all_to_all``
   for use inside ``shard_map`` (per-shard view). This is the direct analog of
   the reference's torch `dist.all_to_all_single` path; on TPU the all-to-all
   rides ICI.
2. `ulysses_spmd` — GSPMD style for use under plain ``jit``: resharding via
   ``with_sharding_constraint`` makes XLA insert the same all-to-alls, with
   the compiler free to overlap them with the qkv projections.
3. `ulysses_flash` — the long-context fast path: explicit all-to-alls
   around the Pallas flash kernel inside a partial-manual ``shard_map``
   (the seq AND model axes are manual when nontrivial; every other axis
   stays GSPMD). The pure-GSPMD form can't use a pallas_call (it doesn't
   auto-partition), so its local attention falls back to XLA, which
   materializes O(S²) logits per head — at the 32k-seq Ulysses operating
   point (blogs/deepspeed-ulysses: 54%-of-peak bar) that is the difference
   between flash-bounded HBM and OOM. The model axis alone also routes
   here: per-head-block kernel, no collectives.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_mesh_context


def seq_all_to_all(x, axis_name: str = "seq", scatter_idx: int = 2, gather_idx: int = 1):
    """All-to-all swapping shard dim, per-shard view (inside shard_map).

    Reference ``sequence/layer.py:41 single_all_to_all``. `scatter_idx` is the
    dim to split across the group (becomes 1/P per device), `gather_idx` the
    dim to concatenate (becomes full). For [b, s/P, h, d] inputs,
    (scatter=2, gather=1) yields [b, s, h/P, d].

    The reference asserts heads % P == 0 (layer.py:53); we do the same at
    trace time.
    """
    p = lax.psum(1, axis_name)
    if x.shape[scatter_idx] % p != 0:
        raise ValueError(
            f"dim {scatter_idx} of shape {x.shape} not divisible by sequence-parallel size {p}")
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx, concat_axis=gather_idx, tiled=True)


class DistributedAttention:
    """Ulysses attention wrapper (reference ``sequence/layer.py:145``).

    Wraps any local attention fn `(q, k, v, *args, **kwargs) -> out` whose
    tensors are [b, s, h, d] per-device views. Must be called inside a
    ``shard_map`` (or ``jit``+manual axes) context where `sequence_axis` is a
    bound mesh axis name.
    """

    def __init__(self,
                 local_attention: Callable,
                 sequence_axis: str = "seq",
                 scatter_idx: int = 2,
                 gather_idx: int = 1):
        self.local_attn = local_attention
        self.axis = sequence_axis
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        # [b, s/P, h, d] -> [b, s, h/P, d]
        q = seq_all_to_all(query, self.axis, self.scatter_idx, self.gather_idx)
        k = seq_all_to_all(key, self.axis, self.scatter_idx, self.gather_idx)
        v = seq_all_to_all(value, self.axis, self.scatter_idx, self.gather_idx)
        out = self.local_attn(q, k, v, *args, **kwargs)
        # [b, s, h/P, d] -> [b, s/P, h, d]
        return seq_all_to_all(out, self.axis, self.gather_idx, self.scatter_idx)


def ulysses_spmd(local_attention: Callable,
                 query,
                 key,
                 value,
                 *args,
                 sequence_axis: str = "seq",
                 mesh_ctx=None,
                 **kwargs):
    """GSPMD Ulysses: express the seq<->head reshard as sharding constraints.

    Under ``jit`` over the global mesh, annotating [b, s@seq, h, d] ->
    [b, s, h@seq, d] makes XLA emit the identical ICI all-to-all the explicit
    path does, but leaves scheduling/overlap to the compiler — the idiomatic
    pjit formulation of reference ``DistributedAttention.forward :181``.
    """
    ctx = mesh_ctx or get_mesh_context()
    sp = ctx.axis_size(sequence_axis)
    if sp == 1:
        return local_attention(query, key, value, *args, **kwargs)
    csr = jax.lax.with_sharding_constraint
    head_spec = ctx.sharding(None, None, sequence_axis, None)
    seq_spec = ctx.sharding(None, sequence_axis, None, None)

    def to_heads(x):
        # GQA: a KV head count not divisible by sp (e.g. 2 kv heads, sp=4)
        # cannot ride the head all-to-all — replicate those instead of
        # forcing the partitioner into a full rematerialization
        if x.shape[2] % sp != 0:
            return csr(x, ctx.sharding(None, None, None, None))
        return csr(x, head_spec)

    q = to_heads(query)
    k = to_heads(key)
    v = to_heads(value)
    out = local_attention(q, k, v, *args, **kwargs)
    return csr(out, seq_spec)


def ulysses_flash(q, k, v, *, window: Optional[int] = None,
                  scale: Optional[float] = None,
                  softcap: Optional[float] = None,
                  sequence_axis: str = "seq", model_axis: str = "model",
                  mesh_ctx=None, interpret: bool = False):
    """Ulysses/TP with the Pallas flash kernel per device (module doc §3).

    [b, S/sp, h/mp, d] inputs under the global mesh → all-to-all over the
    seq axis to [b, S, h/(sp·mp), d] → causal flash over the full sequence
    on the local head block → all-to-all back. Both axes are optional:
    seq-only is classic Ulysses; model-only needs NO collectives (attention
    is embarrassingly parallel over heads) but still gets the kernel, which
    a pallas_call under plain GSPMD cannot (no auto-partitioning). Requires
    heads divisible by sp·mp so the GQA group mapping survives the split
    (any misaligned layout provably reduces to empty per-device KV slices,
    so there is no third layout to fall back to). Returns ``None`` when
    ineligible — the caller falls back to the GSPMD formulation.
    """
    ctx = mesh_ctx or get_mesh_context()
    sp = ctx.axis_size(sequence_axis)
    mp = ctx.axis_size(model_axis)
    if sp == 1 and mp == 1:
        return None
    nq, nkv = q.shape[2], k.shape[2]
    if nq % (sp * mp) or nkv % (sp * mp) or q.shape[1] % sp:
        return None  # heads/sequence must divide the manual axes

    from ..ops.attention import flash_attention

    manual = set()
    if sp > 1:
        manual.add(sequence_axis)
    if mp > 1:
        manual.add(model_axis)

    def body(q_l, k_l, v_l):
        if sp > 1:
            q_l = seq_all_to_all(q_l, sequence_axis, 2, 1)  # [b,S,h/(sp·mp),d]
            k_l = seq_all_to_all(k_l, sequence_axis, 2, 1)
            v_l = seq_all_to_all(v_l, sequence_axis, 2, 1)
        out = flash_attention(q_l, k_l, v_l, causal=True, scale=scale,
                              window=window, softcap=softcap,
                              interpret=interpret)
        if sp > 1:
            out = seq_all_to_all(out, sequence_axis, 1, 2)  # [b,S/sp,h/mp,d]
        return out

    spec = P(None, sequence_axis if sp > 1 else None,
             model_axis if mp > 1 else None, None)
    if not hasattr(jax, "shard_map"):
        # partial-manual shard_map (axis_names=) needs the stable jax API;
        # the older experimental ``auto=`` spelling aborts under the Pallas
        # interpret body — signal ineligible and let the caller take the
        # GSPMD Ulysses formulation instead
        return None
    return jax.shard_map(body, mesh=ctx.mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=frozenset(manual),
                         check_vma=False)(q, k, v)
