"""Ring attention — context parallelism over the ``seq`` mesh axis.

Not present in the reference (SURVEY.md §2.4: "no ring-attention impl; Ulysses
is the long-seq answer") — this is the TPU-native CP extension: K/V shards
rotate around the ring of devices via ``lax.ppermute`` (ICI neighbor
exchanges) while each device keeps its Q shard resident, with flash-style
online-softmax accumulation so the full [s, s] score matrix never
materializes. Communication overlaps compute: block i+1's K/V travels while
block i's scores are on the MXU.

Causal masking uses global positions, so with the default contiguous layout
later ranks do more work than earlier ones; `zigzag` sharding (rank r holds
chunks r and 2P-1-r) balances the causal load — pass ``layout="zigzag"`` and
shard inputs accordingly with `zigzag_split` / `zigzag_unsplit`.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias, o, m, l, scale):
    """One online-softmax accumulation step.

    q [b,sq,h,d], k/v [b,sk,h,d], bias broadcastable to [b,h,sq,sk];
    o [b,sq,h,d] fp32 accumulator, m/l [b,h,sq] running max / normalizer.
    Matmul operands stay in the input dtype (MXU bf16 fast path); fp32
    comes from the dot accumulators (preferred_element_type).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new == NEG_INF) against exp overflow/nan
    m_safe = jnp.where(m_new <= NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= NEG_INF, 0.0, p)
    correction = jnp.exp(jnp.where(m <= NEG_INF, NEG_INF, m - m_safe))
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q,
                   k,
                   v,
                   axis_name: str = "seq",
                   causal: bool = False,
                   scale: Optional[float] = None,
                   layout: str = "contiguous"):
    """Ring attention over per-shard views [b, s/P, h, d] (inside shard_map).

    Returns the attention output for the local Q shard, exact (not
    approximate): equals full softmax attention over the global sequence.
    """
    b, s_local, h, d = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    p = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)

    q_pos = _global_positions(rank, s_local, p, layout)

    # accumulators are seq-varying from birth (shard_map axis-variance
    # tracking: the cond skip-branch and the fori_loop carry both require
    # the branches'/iterations' types to agree). Older jax has no
    # axis-variance tracking (and no lax.pcast) — there the plain arrays
    # are already correct under check_rep=False.
    def _varying(x):
        return (lax.pcast(x, axis_name, to="varying")
                if hasattr(lax, "pcast") else x)

    o = _varying(jnp.zeros(q.shape, jnp.float32))
    m = _varying(jnp.full((b, h, s_local), NEG_INF, jnp.float32))
    l = _varying(jnp.zeros((b, h, s_local), jnp.float32))

    perm = [(i, (i + 1) % p) for i in range(p)]

    def body(i, carry):
        o, m, l, k_cur, v_cur = carry
        kv_rank = (rank - i) % p
        kv_pos = _global_positions(kv_rank, s_local, p, layout)
        if causal:
            # skip ring steps whose K/V shard is ENTIRELY in this Q shard's
            # future (contiguous layout: every step with kv_rank > rank).
            # The per-core scalar cond turns the causal triangle into real
            # skipped FLOPs — ~(P+1)/2P of the dense work on average —
            # while the unconditional ppermute below keeps the ring in
            # lockstep (no collective ever sits inside the branch). The
            # mask is built INSIDE the taken branch so skipped steps pay
            # nothing.
            visible = jnp.min(kv_pos) <= jnp.max(q_pos)

            def _attend(args):
                q_, k_, v_, o_, m_, l_ = args
                mask = kv_pos[None, :] > q_pos[:, None]  # [sq, sk]
                bias = jnp.where(mask, NEG_INF, 0.0)[None, None]
                return _block_attn(q_, k_, v_, bias, o_, m_, l_, scale)

            # the carries are seq-varying from init, so the passthrough
            # matches the compute branch's axis-variance exactly
            o, m, l = lax.cond(
                visible,
                _attend,
                lambda args: (args[3], args[4], args[5]),
                (q, k_cur, v_cur, o, m, l))
        else:
            o, m, l = _block_attn(q, k_cur, v_cur, None, o, m, l, scale)
        # rotate K/V to the next rank (the final hop restores the original
        # shard; unconditional rotation keeps the loop body branch-free)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, p, body, (o, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _global_positions(rank, s_local, p, layout):
    """Global token positions held by `rank` for its local sequence slice."""
    idx = jnp.arange(s_local)
    if layout == "zigzag":
        half = s_local // 2
        lo = rank * half + idx[:half]
        hi = (2 * p - 1 - rank) * half + (idx[half:] - half)
        return jnp.concatenate([lo, hi])
    return rank * s_local + idx


def zigzag_split(x, n_shards: int, axis: int = 1):
    """Reorder a global sequence so contiguous shard r holds zigzag chunks
    (r, 2P-1-r); apply before sharding when using layout='zigzag'."""
    chunks = jnp.split(x, 2 * n_shards, axis=axis)
    order = []
    for r in range(n_shards):
        order += [chunks[r], chunks[2 * n_shards - 1 - r]]
    return jnp.concatenate(order, axis=axis)


def zigzag_unsplit(x, n_shards: int, axis: int = 1):
    """Inverse of `zigzag_split`."""
    chunks = jnp.split(x, 2 * n_shards, axis=axis)
    out = [None] * (2 * n_shards)
    i = 0
    for r in range(n_shards):
        out[r] = chunks[i]
        out[2 * n_shards - 1 - r] = chunks[i + 1]
        i += 2
    return jnp.concatenate(out, axis=axis)
