"""Sequence-parallel cross entropy.

Rebuild of reference ``deepspeed/sequence/cross_entropy.py:11
vocab_sequence_parallel_cross_entropy``: each sequence-parallel rank computes
cross-entropy for its local sequence shard, then the per-token losses are
all-gathered over the ``seq`` axis so every rank sees the full [S, B] loss.

The reference needs a hand-written autograd.Function (the gather is done on
the loss, and the backward re-slices grad_output per rank); under JAX the
gather is differentiable, so plain autodiff produces the same sliced gradient.
"""

import jax.numpy as jnp
from jax import lax


def _log_softmax(x):
    m = lax.stop_gradient(x.max(axis=-1, keepdims=True))
    shifted = x - m
    return shifted - jnp.log(jnp.exp(shifted).sum(axis=-1, keepdims=True))


def vocab_sequence_parallel_cross_entropy(logits, target, axis_name: str = "seq"):
    """Per-token NLL over the sequence-parallel group (inside shard_map).

    logits: [S/P, B, V] local shard; target: [S/P, B].
    Returns [S, B] per-token loss, identical on every rank.
    """
    logp = _log_softmax(logits)
    loss = -jnp.take_along_axis(logp, target[..., None], axis=-1)[..., 0]
    return lax.all_gather(loss, axis_name, axis=0, tiled=True)
