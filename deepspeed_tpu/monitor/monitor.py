"""Monitoring fan-out (reference ``deepspeed/monitor/monitor.py:30``
MonitorMaster → TensorBoard/W&B/Comet/CSV writers). Writers degrade
gracefully when their backend package is absent."""

import os
import csv as _csv
from typing import List, Tuple

import numpy as np

from ..utils.logging import logger


class Monitor:

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    def write_events(self, event_list: List[Tuple]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            log_dir = os.path.join(config.output_path or ".", config.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:  # tensorboard optional
            logger.warning(f"TensorBoard monitor disabled: {e}")
        self.enabled = self.summary_writer is not None

    def write_events(self, event_list, flush=True):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.enabled = False
        try:
            import wandb
            wandb.init(project=config.project, group=config.group, entity=config.team)
            self._wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"wandb monitor disabled: {e}")

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.enabled = True
        self.output_path = config.output_path or "."
        self.job_name = config.job_name
        self.log_dir = os.path.join(self.output_path, self.job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self.filenames = {}  # metric name -> (path, open handle)

    def _writer(self, name: str):
        cached = self.filenames.get(name)
        if cached is not None and not cached[1].closed:
            return cached[1]
        safe = name.replace("/", "_")
        # the dir may have been removed after __init__ (log rotation, tests)
        os.makedirs(self.log_dir, exist_ok=True)
        fn = os.path.join(self.log_dir, f"{safe}.csv")
        new = not os.path.exists(fn) or os.path.getsize(fn) == 0
        fh = open(fn, "a", newline="")
        if new:
            _csv.writer(fh).writerow(["step", safe])
        self.filenames[name] = (fn, fh)
        return fh

    def write_events(self, event_list):
        touched = set()
        for name, value, step in event_list:
            fh = self._writer(name)
            _csv.writer(fh).writerow([step, value])
            touched.add(name)
        for name in touched:  # one flush per batch, not per event
            self.filenames[name][1].flush()

    def close(self):
        for _, fh in self.filenames.values():
            if not fh.closed:
                fh.close()
        self.filenames = {}


class CometMonitor(Monitor):
    """Comet writer (reference monitor/comet.py); degrades gracefully when
    comet_ml is not installed or unauthenticated."""

    def __init__(self, config):
        super().__init__(config)
        self.enabled = False
        try:
            import comet_ml
            # comet_ml.start() (the API the reference uses) accepts every
            # CometConfig field directly — project/workspace/mode/online/
            # api_key/experiment_key
            kw = {k: getattr(config, k) for k in
                  ("project", "workspace", "api_key", "mode", "online",
                   "experiment_key") if getattr(config, k, None) is not None}
            self._exp = comet_ml.start(**kw)
            name = getattr(config, "experiment_name", None)
            if name and hasattr(self._exp, "set_name"):
                self._exp.set_name(name)
            self.enabled = True
        except Exception as e:
            logger.warning(f"comet monitor disabled: {e}")

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._exp.log_metric(name, value, step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled writers (reference monitor.py:30)."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.monitors = []
        if monitor_config.tensorboard.enabled:
            self.monitors.append(TensorBoardMonitor(monitor_config.tensorboard))
        if monitor_config.wandb.enabled:
            self.monitors.append(WandbMonitor(monitor_config.wandb))
        if monitor_config.csv_monitor.enabled:
            self.monitors.append(csvMonitor(monitor_config.csv_monitor))
        if monitor_config.comet.enabled:
            self.monitors.append(CometMonitor(monitor_config.comet))
        self.enabled = len(self.monitors) > 0
        self._deferred = []  # async-pipeline queue of un-fetched events

    def write_events(self, event_list):
        for m in self.monitors:
            m.write_events(event_list)

    def write_registry(self, step, registry=None, prefix="",
                       window_len=None):
        """Bridge the observability metrics registry into the fan-out:
        counters/gauges as scalars, histograms as _count/_mean/_pNN —
        one ``(name, value, step)`` schema shared with training events.

        Async windows pass the WINDOW-START step as ``step`` plus the
        window length (optimizer steps the publish covers), emitted as an
        explicit ``registry_window_steps`` event so a consumer can
        reconstruct the interval [step, step + window_len) instead of
        mis-attributing the whole window to its last step."""
        if not self.enabled:
            return
        if registry is None:
            from ..observability import get_registry
            registry = get_registry()
        events = registry.to_events(step, prefix=prefix)
        if window_len is not None:
            events.append((f"{prefix}registry_window_steps",
                           float(window_len), step))
        self.write_events(events)

    def write_events_async(self, event_list):
        """Queue events WITHOUT forcing a device→host sync (async-pipeline
        variant): ``value`` may be a live device scalar — or a device vector
        paired with a list of per-element steps (the K-step fused dispatch
        shape). Nothing is fetched until :meth:`flush_events`."""
        if self.enabled:
            self._deferred.extend(event_list)

    def flush_events(self, fetch=None):
        """Resolve every queued event in ONE batched device→host transfer
        and fan it out to the writers. ``fetch``: the transfer function
        (defaults to ``jax.device_get``); the engine passes its own seam so
        sync accounting stays observable."""
        if not self._deferred:
            return
        deferred, self._deferred = self._deferred, []
        if not self.enabled:
            return
        if fetch is None:
            import jax
            fetch = jax.device_get
        values = fetch([v for (_, v, _) in deferred])
        out = []
        for (name, _, step), v in zip(deferred, values):
            a = np.asarray(v)
            if a.ndim:  # vector event: one value per fused sub-step
                out.extend((name, float(x), int(s)) for x, s in zip(a, step))
            else:
                out.append((name, float(a), int(step)))
        self.write_events(out)
