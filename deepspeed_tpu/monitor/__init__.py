from .monitor import MonitorMaster, TensorBoardMonitor, WandbMonitor, csvMonitor
