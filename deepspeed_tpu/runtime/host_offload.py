"""ZeRO-Offload: optimizer stepping on the host CPU (optionally NVMe-backed).

Reference: ``csrc/adam/cpu_adam_impl.cpp`` (AVX-vectorized host Adam) +
``runtime/zero/stage_1_and_2.py`` cpu-offload grad path +
``runtime/swap_tensor/partitioned_optimizer_swapper.py``. The point of
ZeRO-Offload: fp32 master weights + Adam moments live in host DRAM (or
NVMe), freeing HBM for params/activations; gradients stream device→host
each boundary, the host does the optimizer math, updated weights stream
back.

TPU build: the host step is vectorized numpy (BLAS/SIMD under the hood —
the same machine resources the reference's hand-written AVX loop uses).
With ``device: nvme`` the moments round-trip through the C++ AIO swapper
between steps, double-buffered per parameter group
(``PipelinedOptimizerSwapper``).

The math matches optax exactly (adam/adamw bias correction, decoupled
weight decay) so host-offloaded runs are numerically interchangeable with
on-device runs — verified by tests.
"""

from typing import Dict, List, Optional, Tuple

import numpy as np
import jax

from ..utils.logging import logger


class HostAdamOptimizer:
    """fp32 master weights + moments on host; step() in numpy.

    adam:  torch-style L2 (decay folded into the gradient).
    adamw: decoupled decay (update includes wd·p scaled by lr) — optax.adamw.
    """

    def __init__(self, params_host: Dict[str, np.ndarray], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 nvme_swapper=None, lr_fn=None):
        self.master = {k: np.asarray(v, dtype=np.float32).copy()
                       for k, v in params_host.items()}
        self.lr = lr
        self.lr_fn = lr_fn
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.adamw_mode = adamw_mode
        self.t = 0
        self._swapper = nvme_swapper
        if nvme_swapper is None:
            self.m = {k: np.zeros_like(v) for k, v in self.master.items()}
            self.v = {k: np.zeros_like(v) for k, v in self.master.items()}
        else:  # moments live on NVMe between steps
            self.m = self.v = None
            for k, w in self.master.items():
                nvme_swapper.swap_out_optimizer_state(
                    k, {"exp_avg": np.zeros_like(w), "exp_avg_sq": np.zeros_like(w)})

    def _cur_lr(self) -> float:
        return float(self.lr_fn(self.t)) if self.lr_fn is not None else self.lr

    def _step_one(self, name: str, g: np.ndarray, m: np.ndarray, v: np.ndarray):
        p = self.master[name]
        if self.wd and not self.adamw_mode:
            g = g + self.wd * p  # L2 into the gradient (torch Adam)
        m *= self.b1
        m += (1 - self.b1) * g
        v *= self.b2
        v += (1 - self.b2) * g * g
        mhat = m / (1 - self.b1**self.t)
        vhat = v / (1 - self.b2**self.t)
        update = mhat / (np.sqrt(vhat) + self.eps)
        if self.wd and self.adamw_mode:
            update = update + self.wd * p
        p -= self._cur_lr() * update
        return m, v

    def step(self, grads_host: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """One optimizer step over all params; returns the updated master."""
        self.t += 1
        if self._swapper is None:
            for k, g in grads_host.items():
                self._step_one(k, np.asarray(g, np.float32), self.m[k], self.v[k])
        else:
            names = list(grads_host.keys())
            # pipelined: prefetch next group's moments while stepping current
            self._swapper._swapper.swap_in([f"{names[0]}.exp_avg", f"{names[0]}.exp_avg_sq"],
                                           async_op=True)
            for i, k in enumerate(names):
                if i + 1 < len(names):
                    nxt = names[i + 1]
                    self._swapper._swapper.swap_in([f"{nxt}.exp_avg", f"{nxt}.exp_avg_sq"],
                                                   async_op=True)
                state = {kk: self._swapper._swapper.retrieve(f"{k}.{kk}")
                         for kk in ("exp_avg", "exp_avg_sq")}
                m, v = self._step_one(k, np.asarray(grads_host[k], np.float32),
                                      state["exp_avg"], state["exp_avg_sq"])
                for kk, arr in (("exp_avg", m), ("exp_avg_sq", v)):
                    self._swapper._swapper.swap_out_and_release(f"{k}.{kk}", arr)
            self._swapper._swapper.synchronize_writes()
        return self.master

    def state_dict(self) -> dict:
        sd = {"t": self.t, "master": self.master}
        if self._swapper is None:
            sd["m"], sd["v"] = self.m, self.v
        return sd

    def load_state_dict(self, sd: dict) -> None:
        self.t = sd["t"]
        self.master = {k: np.asarray(v, np.float32) for k, v in sd["master"].items()}
        if self._swapper is None and "m" in sd:
            self.m, self.v = sd["m"], sd["v"]


def flatten_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flatten_tree(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = tree
    return out


def unflatten_like(flat: Dict[str, np.ndarray], like):
    def rebuild(node, prefix=""):
        if isinstance(node, dict):
            return {k: rebuild(v, f"{prefix}{k}.") for k, v in node.items()}
        return flat[prefix[:-1]]
    return rebuild(like)
