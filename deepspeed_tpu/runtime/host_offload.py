"""ZeRO-Offload: optimizer stepping on the host CPU (optionally NVMe-backed).

Reference: ``csrc/adam/cpu_adam_impl.cpp`` (AVX-vectorized host Adam) +
``runtime/zero/stage_1_and_2.py`` cpu-offload grad path +
``runtime/swap_tensor/partitioned_optimizer_swapper.py``. The point of
ZeRO-Offload: fp32 master weights + Adam moments live in host DRAM (or
NVMe), freeing HBM for params/activations; gradients stream device→host
each boundary, the host does the optimizer math, updated weights stream
back.

TPU build: the host step is vectorized numpy (BLAS/SIMD under the hood —
the same machine resources the reference's hand-written AVX loop uses).
With ``device: nvme`` the moments round-trip through the C++ AIO swapper
between steps, double-buffered per parameter group
(``PipelinedOptimizerSwapper``).

The math matches optax exactly (adam/adamw bias correction, decoupled
weight decay) so host-offloaded runs are numerically interchangeable with
on-device runs — verified by tests.
"""

from typing import Dict, List, Optional, Tuple

import numpy as np
import jax

from ..utils.logging import logger


class HostAdamOptimizer:
    """fp32 master weights + optimizer state on host; step() through the C++
    SIMD kernels (ops/cpu_optim.py ≙ reference csrc/adam/cpu_adam_impl.cpp
    Step_AVX) with a numpy fallback.

    mode:
      adam:    torch-style L2 (decay folded into the gradient).
      adamw:   decoupled decay (update includes wd·p) — optax.adamw.
      adagrad: optax.adagrad (scale_by_rss, accumulator init 0.1); state is
               the squared-grad sum riding the exp_avg_sq slot.
      lion:    optax.lion (sign of the b1 interpolation, decoupled decay);
               momentum rides the exp_avg slot, no second state.
    """

    _MODE_STATES = {"adam": ("m", "v"), "adamw": ("m", "v"),
                    "adagrad": ("v", ), "lion": ("m", )}

    def __init__(self, params_host: Dict[str, np.ndarray], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 nvme_swapper=None, lr_fn=None, master_swapper=None,
                 mode: Optional[str] = None,
                 initial_accumulator_value: float = 0.1):
        self.mode = mode or ("adamw" if adamw_mode else "adam")
        assert self.mode in self._MODE_STATES, self.mode
        self.lr = lr
        self.lr_fn = lr_fn
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.adamw_mode = self.mode == "adamw"
        self.t = 0
        self._swapper = nvme_swapper
        self._master_swapper = master_swapper
        if master_swapper is None:
            self.master = {k: np.asarray(v, dtype=np.float32).copy()
                           for k, v in params_host.items()}
        else:
            # fp32 master lives ON NVMe (ZeRO-Infinity params_in_nvme): DRAM
            # holds one leaf at a time during step/serve
            self.master = {}
            self._master_keys = list(params_host.keys())
            for k, v in params_host.items():
                master_swapper.swap_out_and_release(k, np.asarray(v, np.float32))
            master_swapper.synchronize_writes()
        states = self._MODE_STATES[self.mode]
        v_init = initial_accumulator_value if self.mode == "adagrad" else 0.0

        def _zeros(v, fill):
            z = np.zeros_like(np.asarray(v), dtype=np.float32)
            if fill:
                z += fill
            return z

        if nvme_swapper is None:
            self.m = ({k: _zeros(v, 0.0) for k, v in params_host.items()}
                      if "m" in states else None)
            self.v = ({k: _zeros(v, v_init) for k, v in params_host.items()}
                      if "v" in states else None)
        else:  # moments live on NVMe between steps
            if self.mode not in ("adam", "adamw"):
                raise ValueError("NVMe optimizer-state offload supports "
                                 "adam/adamw only")
            self.m = self.v = None
            for k, w in params_host.items():
                nvme_swapper.swap_out_optimizer_state(
                    k, {"exp_avg": _zeros(w, 0.0), "exp_avg_sq": _zeros(w, 0.0)})

    @property
    def param_names(self):
        return (self._master_keys if self._master_swapper is not None
                else list(self.master.keys()))

    def read_master(self, name: str) -> np.ndarray:
        """Fetch one master leaf (from DRAM, or NVMe in master-swapper mode)."""
        if self._master_swapper is None:
            return self.master[name]
        self._master_swapper.swap_in([name], async_op=False)
        return self._master_swapper.retrieve(name)

    def prefetch_master(self, names) -> None:
        if self._master_swapper is not None:
            self._master_swapper.swap_in(list(names), async_op=True)

    def _cur_lr(self) -> float:
        return float(self.lr_fn(self.t)) if self.lr_fn is not None else self.lr

    def _step_one(self, p: np.ndarray, g: np.ndarray, m, v):
        """One leaf's update, in place. Dispatches to the C++ SIMD kernel
        when the native lib built; numpy otherwise (identical numerics)."""
        from ..ops import cpu_optim
        lr = self._cur_lr()
        if self.mode in ("adam", "adamw"):
            if cpu_optim.adam_step(p, g, m, v, lr=lr, b1=self.b1, b2=self.b2,
                                   eps=self.eps, wd=self.wd,
                                   adamw=self.adamw_mode, step=self.t):
                return m, v
            if self.wd and not self.adamw_mode:
                g = g + self.wd * p  # L2 into the gradient (torch Adam)
            m *= self.b1
            m += (1 - self.b1) * g
            v *= self.b2
            v += (1 - self.b2) * g * g
            mhat = m / (1 - self.b1**self.t)
            vhat = v / (1 - self.b2**self.t)
            update = mhat / (np.sqrt(vhat) + self.eps)
            if self.wd and self.adamw_mode:
                update = update + self.wd * p
            p -= lr * update
            return m, v
        if self.mode == "adagrad":
            # optax.adagrad takes no weight decay; neither does this path
            if cpu_optim.adagrad_step(p, g, v, lr=lr, eps=self.eps):
                return m, v
            v += g * g
            p -= lr * g / np.sqrt(v + self.eps)
            return m, v
        # lion (optax.lion semantics)
        if cpu_optim.lion_step(p, g, m, lr=lr, b1=self.b1, b2=self.b2, wd=self.wd):
            return m, v
        c = self.b1 * m + (1 - self.b1) * g
        update = np.sign(c)
        if self.wd:
            update = update + self.wd * p
        p -= lr * update
        m *= self.b2
        m += (1 - self.b2) * g
        return m, v

    # -- streaming per-param API: lets the engine interleave host math with
    # device<->host transfers (reference pipelined_optimizer_swapper.py) --

    def step_begin(self):
        self.t += 1

    def step_param(self, name: str, g: np.ndarray,
                   prefetch: Optional[str] = None) -> np.ndarray:
        """Step ONE param; returns its updated master. `prefetch` kicks the
        async NVMe read of the next param's moments/master (double buffering)."""
        g = np.asarray(g, np.float32)
        if prefetch is not None:
            self.prefetch_master([prefetch])
        p = self.read_master(name)
        if self._swapper is None:
            self._step_one(p, g,
                           self.m[name] if self.m is not None else None,
                           self.v[name] if self.v is not None else None)
        else:
            sw = self._swapper._swapper
            sw.swap_in([f"{name}.exp_avg", f"{name}.exp_avg_sq"], async_op=True)
            if prefetch is not None:
                sw.swap_in([f"{prefetch}.exp_avg", f"{prefetch}.exp_avg_sq"],
                           async_op=True)
            m = sw.retrieve(f"{name}.exp_avg")
            v = sw.retrieve(f"{name}.exp_avg_sq")
            m, v = self._step_one(p, g, m, v)
            sw.swap_out_and_release(f"{name}.exp_avg", m)
            sw.swap_out_and_release(f"{name}.exp_avg_sq", v)
        if self._master_swapper is not None:
            self._master_swapper.swap_out_and_release(name, p)
        return p

    def step_end(self):
        if self._swapper is not None:
            self._swapper._swapper.synchronize_writes()
        if self._master_swapper is not None:
            self._master_swapper.synchronize_writes()

    def step(self, grads_host: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """One optimizer step over all params; returns the updated masters
        (DRAM mode: the live dict; NVMe-master mode: a transient copy)."""
        self.step_begin()
        names = list(grads_host.keys())
        out = {}
        for i, k in enumerate(names):
            out[k] = self.step_param(
                k, grads_host[k],
                prefetch=names[i + 1] if i + 1 < len(names) else None)
        self.step_end()
        return self.master if self._master_swapper is None else out

    def state_dict(self) -> dict:
        """Full optimizer state, NVMe-resident pieces included (a checkpoint
        that silently dropped the moments would 'resume' with reset Adam)."""
        sd = {"t": self.t}
        sd["master"] = ({k: self.read_master(k) for k in self.param_names}
                        if self._master_swapper is not None else self.master)
        if self._swapper is None:
            if self.m is not None:
                sd["m"] = self.m
            if self.v is not None:
                sd["v"] = self.v
        else:
            sw = self._swapper._swapper
            m, v = {}, {}
            for k in self.param_names:
                sw.swap_in([f"{k}.exp_avg", f"{k}.exp_avg_sq"], async_op=False)
                m[k] = sw.retrieve(f"{k}.exp_avg")
                v[k] = sw.retrieve(f"{k}.exp_avg_sq")
            sd["m"], sd["v"] = m, v
        return sd

    # -- leaf-streamed checkpoint files: state_dict() materializes the whole
    # master+moments in DRAM, which NVMe-offloaded models may not fit; these
    # write/read ONE leaf at a time --

    @staticmethod
    def _safe(name: str) -> str:
        # percent-encode: injective, so distinct param names can never
        # collide onto one checkpoint file
        from urllib.parse import quote
        return quote(name, safe="")

    def save_state_files(self, path: str) -> None:
        import json
        import os
        os.makedirs(path, exist_ok=True)
        names = self.param_names
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"t": self.t, "names": names}, f)
        for k in names:
            base = os.path.join(path, self._safe(k))
            np.save(base + ".master.npy", self.read_master(k))
            if self._swapper is None:
                m = self.m[k] if self.m is not None else None
                v = self.v[k] if self.v is not None else None
            else:
                sw = self._swapper._swapper
                sw.swap_in([f"{k}.exp_avg", f"{k}.exp_avg_sq"], async_op=False)
                m = sw.retrieve(f"{k}.exp_avg")
                v = sw.retrieve(f"{k}.exp_avg_sq")
            if m is not None:
                np.save(base + ".m.npy", m)
            if v is not None:
                np.save(base + ".v.npy", v)

    def load_state_files(self, path: str) -> None:
        import json
        import os
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self.t = meta["t"]
        needed = self._MODE_STATES[self.mode]
        for k in meta["names"]:
            base = os.path.join(path, self._safe(k))
            master = np.load(base + ".master.npy")
            if self._master_swapper is None:
                self.master[k] = master
            else:
                self._master_swapper.swap_out_and_release(k, master)

            def _load_state(tag):
                fn = base + f".{tag}.npy"
                if not os.path.exists(fn):
                    # missing state for this mode = a silently-reset optimizer
                    raise FileNotFoundError(
                        f"checkpoint is missing {fn} (mode={self.mode} needs "
                        f"'{tag}'); refusing to resume with reset moments")
                return np.load(fn)

            m = _load_state("m") if "m" in needed else None
            v = _load_state("v") if "v" in needed else None
            if self._swapper is None:
                if m is not None:
                    self.m[k] = m
                if v is not None:
                    self.v[k] = v
            else:  # NVMe moments: adam/adamw only (both states present)
                self._swapper.swap_out_optimizer_state(
                    k, {"exp_avg": m, "exp_avg_sq": v})
        if self._master_swapper is not None:
            self._master_keys = list(meta["names"])
            self._master_swapper.synchronize_writes()

    def load_state_dict(self, sd: dict) -> None:
        self.t = sd["t"]
        masters = {k: np.asarray(v, np.float32) for k, v in sd["master"].items()}
        if self._master_swapper is None:
            self.master = masters
        else:
            self._master_keys = list(masters.keys())
            for k, v in masters.items():
                self._master_swapper.swap_out_and_release(k, v)
            self._master_swapper.synchronize_writes()
        needed = self._MODE_STATES[self.mode]
        missing = [t for t in needed if t not in sd]
        if missing:
            raise KeyError(f"host optimizer state_dict missing {missing} "
                           f"(mode={self.mode}); refusing a silent reset")
        if self._swapper is None:
            if "m" in sd:
                self.m = sd["m"]
            if "v" in sd:
                self.v = sd["v"]
        elif "m" in sd:
            for k in sd["m"]:
                self._swapper.swap_out_optimizer_state(
                    k, {"exp_avg": np.asarray(sd["m"][k], np.float32),
                        "exp_avg_sq": np.asarray(sd["v"][k], np.float32)})


def flatten_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flatten_tree(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = tree
    return out


def unflatten_like(flat: Dict[str, np.ndarray], like):
    def rebuild(node, prefix=""):
        if isinstance(node, dict):
            return {k: rebuild(v, f"{prefix}{k}.") for k, v in node.items()}
        return flat[prefix[:-1]]
    return rebuild(like)
