"""Sparse tensor for embedding-style sparse gradients.

Reference: ``runtime/sparse_tensor.py SparseTensor`` — wraps torch sparse
grads so the engine's sparse allreduce (engine.py:2518) can gather
index/value pairs. TPU version: a COO (indices, values, dense_shape) pytree;
the sparse allreduce analog is an all_gather of indices+values followed by
a segment-sum on device."""

from typing import Tuple

import jax
import jax.numpy as jnp


class SparseTensor:

    def __init__(self, indices, values, dense_shape: Tuple[int, ...]):
        self.indices = jnp.asarray(indices, dtype=jnp.int32)  # [nnz]
        self.values = jnp.asarray(values)                     # [nnz, ...]
        self.dense_shape = tuple(dense_shape)

    @staticmethod
    def from_dense(x, rows_nonzero=None) -> "SparseTensor":
        """Row-sparse view (embedding grads are row-sparse)."""
        if rows_nonzero is None:
            rows_nonzero = jnp.nonzero(jnp.any(x != 0, axis=tuple(range(1, x.ndim))))[0]
        return SparseTensor(rows_nonzero, x[rows_nonzero], x.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, dtype=self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> int:
        return int(self.indices.size + self.values.size)

    @property
    def dense_size(self) -> int:
        import numpy as np
        return int(np.prod(self.dense_shape))

    def __repr__(self):
        return (f"SparseTensor(nnz={int(self.indices.size)}, "
                f"dense_shape={self.dense_shape})")


class _StaticIndices:
    """Hashable wrapper so indices live in pytree aux data — numeric
    tree_maps (loss scaling, clipping, dtype casts) must only touch values;
    mapping over indices would silently move entries to wrong rows."""

    def __init__(self, arr):
        import numpy as np
        self.arr = np.asarray(arr, dtype=np.int32)
        self._key = self.arr.tobytes()

    def __eq__(self, other):
        return isinstance(other, _StaticIndices) and self._key == other._key

    def __hash__(self):
        return hash(self._key)


jax.tree_util.register_pytree_node(
    SparseTensor,
    lambda st: ((st.values, ), (_StaticIndices(st.indices), st.dense_shape)),
    lambda aux, kids: SparseTensor(aux[0].arr, kids[0], aux[1]))
