"""SPMD pipeline executors: scan over ticks + ppermute over the pipe axis.

The TPU-native realization of the reference's executors
(``runtime/pipe/engine.py:1406 _exec_schedule`` dispatching p2p send/recv):
under single-controller SPMD every stage runs the same program, so a
schedule becomes a ``lax.scan`` over ticks with ``lax.ppermute`` as the
neighbor exchange (the p2p of ``pipe/p2p.py``).

Two executors:

- :func:`spmd_pipeline` — forward pipeline; reverse-mode autodiff of the
  scan yields the backward pipeline, but only after ALL forward ticks — so
  its live-activation set is O(M) microbatches (GPipe memory;
  reference ``pipe/schedule.py:135 InferenceSchedule`` semantics). Kept for
  inference/eval and as the autodiff oracle.

- :func:`spmd_pipeline_1f1b` — the 1F1B TRAIN schedule (reference
  ``pipe/schedule.py:189 TrainSchedule``): forward and backward interleave
  in ONE scan. Stage ``s`` runs F(m) at tick ``s + 2m`` and B(m) at tick
  ``2S-1-s + 2m`` — F/B strictly alternate per stage (the steady-state
  one-forward-one-backward cadence, cf. TrainSchedule's alternating
  instruction pairs), backward for a microbatch starts as soon as its
  forward reaches the last stage, and each stage keeps only its in-flight
  window: a depth-``S`` stash of stage INPUTS (recomputed through
  ``jax.vjp`` at B — activation remat). Live activation memory is O(S·mb),
  INDEPENDENT of the microbatch count M — 1F1B's defining property
  (reference ``pipe/schedule.py:217 num_pipe_buffers``). The loss head runs
  inside the last stage and ingest/embed inside stage 0, so no [M, ...]
  activation buffer exists anywhere; per-stage parameter gradients
  accumulate across microbatches inside the scan.
"""

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def spmd_pipeline(stage_fn: Callable,
                  stage_params,
                  microbatches,
                  axis_name: str = "pipe"):
    """Run `stage_fn(stage_params, x)` as a pipeline over the `axis_name` axis.

    Must be called inside shard_map with `axis_name` bound.

    Args:
      stage_fn: applies ONE stage's layers; activations in == activations out
        shape (homogeneous pipeline body — embeddings/heads run outside).
      stage_params: this stage's parameter pytree (per-shard view; leading
        stage dim already consumed by shard_map's in_spec).
      microbatches: [M, mb, ...] activation microbatches (replicated across
        the pipe axis; only stage 0 reads them).

    Returns [M, mb, ...] outputs, valid on every stage (psum-broadcast from
    the last stage).
    """
    S = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = M + S - 1

    first = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped; masked by position anyway)
        inp = microbatches[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(sid == 0, inp, state)
        y = stage_fn(stage_params, cur)
        # last stage banks microbatch m = t - (S-1)
        m = t - (S - 1)
        banked = outputs.at[jnp.clip(m, 0, M - 1)].set(y)
        outputs = jnp.where((sid == S - 1) & (m >= 0), banked, outputs)
        # rotate activations to the next stage (ring; wraparound is ignored
        # by stage 0, which reads fresh input)
        state = lax.ppermute(y, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (first, outputs), jnp.arange(ticks))
    # broadcast final activations from the last stage to all stages
    mask = (sid == S - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)


# ---------------------------------------------------------------------------
# 1F1B interleaved train executor
# ---------------------------------------------------------------------------


def _tree_take(tree, i):
    return jax.tree_util.tree_map(lambda x: x[i], tree)


def _tree_zeros_f32(tree):
    return jax.tree_util.tree_map(lambda x: jnp.zeros(jnp.shape(x), jnp.float32), tree)


def _tree_add_masked(acc, delta, mask):
    return jax.tree_util.tree_map(
        lambda a, d: a + jnp.where(mask, d.astype(a.dtype), 0), acc, delta)


def spmd_pipeline_1f1b(stage_fn: Callable,
                       ingest_fn: Callable,
                       head_fn: Callable,
                       body_params,
                       embed_params,
                       head_params,
                       in_mbs,
                       tgt_mbs,
                       axis_name: str = "pipe"):
    """One fused 1F1B train pass over the ``axis_name`` pipeline axis.

    Must run inside shard_map with ``axis_name`` manual. Per tick each stage
    executes EITHER one forward or one backward micro-step (lax.cond on the
    tick parity — never both), exchanging activations downstream and
    gradients upstream via two ppermutes.

    Args:
      stage_fn(body_params, x) -> y: this stage's layer block.
      ingest_fn(embed_params, in_mb) -> activations: runs ONLY on stage 0
        (embedding); in_mb is one microbatch of raw inputs (a pytree).
      head_fn(head_params, y, tgt_mb) -> scalar microbatch loss: runs ONLY
        on the last stage.
      in_mbs / tgt_mbs: [M, mb, ...] pytrees of raw inputs / targets.

    Returns (mean_loss, dbody, dembed, dhead): loss and UNSCALED parameter
    gradients (cotangent 1/M per microbatch — i.e. grads of the mean loss).
    dbody is this stage's shard; dembed/dhead are psum-broadcast.
    """
    S = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = jax.tree_util.tree_leaves(in_mbs)[0].shape[0]

    act = jax.eval_shape(ingest_fn, embed_params, _tree_take(in_mbs, 0))
    zeros_act = jnp.zeros(act.shape, act.dtype)
    K = S  # stash depth: in-flight microbatches per stage <= S - sid <= S

    def fwd_full(body_p, embed_p, in_mb, h_in):
        """Stage 0 embeds raw inputs; later stages take the incoming
        activation. One function so jax.vjp covers embed grads too. The
        lax.cond keeps the embedding gather (and its dense [V, d] scatter in
        the vjp) off every stage but 0."""
        x = lax.cond(sid == 0,
                     lambda _: ingest_fn(embed_p, in_mb).astype(h_in.dtype),
                     lambda _: h_in, None)
        return stage_fn(body_p, x)

    carry0 = dict(
        fwd=zeros_act,                    # activation arriving from upstream
        bwd=zeros_act,                    # gradient arriving from downstream
        dy_pend=zeros_act,                # last stage: head grad awaiting its B tick
        stash_h=jnp.zeros((K, *act.shape), act.dtype),
        stash_in=jax.tree_util.tree_map(
            lambda x: jnp.zeros((K, *x.shape[1:]), x.dtype), in_mbs),
        loss=jnp.float32(0.0),
        dbody=_tree_zeros_f32(body_params),
        dembed=_tree_zeros_f32(embed_params),
        dhead=_tree_zeros_f32(head_params),
    )

    def tick(c, t):
        is_f = ((t - sid) % 2) == 0
        mf = (t - sid) // 2                       # F(mf) at tick sid + 2*mf
        mb_ = (t - (2 * S - 1 - sid)) // 2        # B(mb_) at tick 2S-1-sid + 2*mb_
        mf_c = jnp.clip(mf, 0, M - 1)
        mb_c = jnp.clip(mb_, 0, M - 1)
        f_valid = is_f & (mf >= 0) & (mf < M)
        b_valid = (~is_f) & (mb_ >= 0) & (mb_ < M)

        def f_branch(c):
            in_mb = _tree_take(in_mbs, mf_c)
            y = fwd_full(body_params, embed_params, in_mb, c["fwd"])
            is_last = sid == S - 1

            def with_head(_):
                loss_m, vjp_h = jax.vjp(
                    lambda hp, yy: head_fn(hp, yy, _tree_take(tgt_mbs, mf_c))
                    .astype(jnp.float32), head_params, y)
                dh_m, dy = vjp_h(jnp.float32(1.0 / M))
                return loss_m, dh_m, dy.astype(zeros_act.dtype)

            def no_head(_):
                return (jnp.float32(0.0),
                        jax.tree_util.tree_map(jnp.zeros_like, head_params),
                        zeros_act)

            loss_m, dh_m, dy = lax.cond(is_last, with_head, no_head, None)
            commit = f_valid & is_last
            nc = dict(c)
            nc["loss"] = c["loss"] + jnp.where(commit, loss_m / M, 0.0)
            nc["dhead"] = _tree_add_masked(c["dhead"], dh_m, commit)
            nc["dy_pend"] = jnp.where(commit, dy, c["dy_pend"])
            slot = mf_c % K

            def set_stash(st, val):
                return st.at[slot].set(jnp.where(f_valid, val, st[slot]))

            nc["stash_h"] = set_stash(c["stash_h"], c["fwd"])
            nc["stash_in"] = jax.tree_util.tree_map(set_stash, c["stash_in"], in_mb)
            return nc, y, zeros_act

        def b_branch(c):
            slot = mb_c % K
            x_in = _tree_take(c["stash_in"], slot)
            dy_in = jnp.where(sid == S - 1, c["dy_pend"], c["bwd"])
            # recompute the stage forward from its saved INPUT (remat), take
            # the vjp wrt body/embed params and the incoming activation
            _, vjp = jax.vjp(
                lambda bp, ep, h: fwd_full(bp, ep, x_in, h),
                body_params, embed_params, c["stash_h"][slot])
            db_m, de_m, dx = vjp(dy_in)
            nc = dict(c)
            nc["dbody"] = _tree_add_masked(c["dbody"], db_m, b_valid)
            nc["dembed"] = _tree_add_masked(c["dembed"], de_m, b_valid & (sid == 0))
            return nc, zeros_act, dx.astype(zeros_act.dtype)

        nc, y_down, dx_up = lax.cond(is_f, f_branch, b_branch, c)
        # collectives run unconditionally (every device must participate);
        # receivers only read the buffer on the matching parity tick
        nc["fwd"] = lax.ppermute(y_down, axis_name,
                                 [(i, (i + 1) % S) for i in range(S)])
        nc["bwd"] = lax.ppermute(dx_up, axis_name,
                                 [(i, (i - 1) % S) for i in range(S)])
        return nc, None

    c, _ = lax.scan(tick, carry0, jnp.arange(2 * (M + S - 1)))
    loss = lax.psum(c["loss"], axis_name)  # nonzero only on the last stage
    dhead = jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), c["dhead"])
    dembed = jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), c["dembed"])
    return loss, c["dbody"], dembed, dhead


def spmd_pipeline_eval(stage_fn: Callable,
                       ingest_fn: Callable,
                       head_fn: Callable,
                       body_params,
                       embed_params,
                       head_params,
                       in_mbs,
                       tgt_mbs,
                       axis_name: str = "pipe"):
    """Forward-only pipeline returning the mean loss (InferenceSchedule
    cadence: one F per stage per tick, M + S - 1 ticks, no stash)."""
    S = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = jax.tree_util.tree_leaves(in_mbs)[0].shape[0]
    act = jax.eval_shape(ingest_fn, embed_params, _tree_take(in_mbs, 0))
    zeros_act = jnp.zeros(act.shape, act.dtype)

    def fwd_full(in_mb, h_in):
        h0 = ingest_fn(embed_params, in_mb).astype(h_in.dtype)
        return stage_fn(body_params, jnp.where(sid == 0, h0, h_in))

    def tick(carry, t):
        fwd, loss = carry
        m = t - sid
        m_c = jnp.clip(m, 0, M - 1)
        valid = (m >= 0) & (m < M)
        y = fwd_full(_tree_take(in_mbs, m_c), fwd)
        is_last = sid == S - 1
        loss_m = lax.cond(
            is_last,
            lambda _: head_fn(head_params, y, _tree_take(tgt_mbs, m_c))
            .astype(jnp.float32),
            lambda _: jnp.float32(0.0), None)
        loss = loss + jnp.where(valid & is_last, loss_m / M, 0.0)
        fwd = lax.ppermute(y, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return (fwd, loss), None

    (_, loss), _ = lax.scan(tick, (zeros_act, jnp.float32(0.0)),
                            jnp.arange(M + S - 1))
    return lax.psum(loss, axis_name)
