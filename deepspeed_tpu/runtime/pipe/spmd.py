"""SPMD pipeline executor: scan over ticks + ppermute over the pipe axis.

The TPU-native realization of the reference's 1F1B executor
(``runtime/pipe/engine.py:1406 _exec_schedule`` dispatching p2p send/recv):
under single-controller SPMD every stage runs the same program, so the
schedule becomes a ``lax.scan`` over ticks where each tick

    1. stage 0 ingests microbatch t,
    2. every stage applies its layer block to its current buffer,
    3. ``lax.ppermute`` shifts activations one stage down the ring (ICI
       neighbor exchange — the p2p of ``pipe/p2p.py``),
    4. the last stage banks its result for microbatch t-(S-1).

Reverse-mode autodiff of the scan + ppermute yields exactly the backward
pipeline (grads ppermute upstream), so BackwardPass/SendGrad/RecvGrad need no
hand-written executor. Ramp-up/down bubbles compute garbage that is masked at
collection — the same bubble cost as GPipe/1F1B (fraction (S-1)/(M+S-1)).
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def spmd_pipeline(stage_fn: Callable,
                  stage_params,
                  microbatches,
                  axis_name: str = "pipe"):
    """Run `stage_fn(stage_params, x)` as a pipeline over the `axis_name` axis.

    Must be called inside shard_map with `axis_name` bound.

    Args:
      stage_fn: applies ONE stage's layers; activations in == activations out
        shape (homogeneous pipeline body — embeddings/heads run outside).
      stage_params: this stage's parameter pytree (per-shard view; leading
        stage dim already consumed by shard_map's in_spec).
      microbatches: [M, mb, ...] activation microbatches (replicated across
        the pipe axis; only stage 0 reads them).

    Returns [M, mb, ...] outputs, valid on every stage (psum-broadcast from
    the last stage).
    """
    S = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = M + S - 1

    first = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped; masked by position anyway)
        inp = microbatches[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(sid == 0, inp, state)
        y = stage_fn(stage_params, cur)
        # last stage banks microbatch m = t - (S-1)
        m = t - (S - 1)
        banked = outputs.at[jnp.clip(m, 0, M - 1)].set(y)
        outputs = jnp.where((sid == S - 1) & (m >= 0), banked, outputs)
        # rotate activations to the next stage (ring; wraparound is ignored
        # by stage 0, which reads fresh input)
        state = lax.ppermute(y, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (first, outputs), jnp.arange(ticks))
    # broadcast final activations from the last stage to all stages
    mask = (sid == S - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)
