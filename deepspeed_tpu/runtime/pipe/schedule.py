"""Pipeline schedules, derived from a declarative dependency DAG.

Capability parity with reference ``runtime/pipe/schedule.py`` (1F1B train
schedule, fill-drain inference schedule, instruction-name API), but the
derivation is different by design: instead of per-rank closed-form index
formulas, a tiny discrete-time list scheduler simulates the whole pipeline
against an explicit dependency DAG:

    F(m, s)  needs  F(m, s-1) finished one tick earlier   (activation hop)
    B(m, s)  needs  B(m, s+1) finished one tick earlier   (gradient hop)
                and F(m, s)                               (own forward)

plus the 1F1B memory policy — a stage may start a new forward only while
``live(s) < min(stages - s, micro_batches)`` microbatches are in flight —
and a backward-first priority rule. 1F1B is *emergent* from those three
declarative facts rather than hand-scheduled, the simulation gives every
stage a shared global clock (what the SPMD tick executor in ``spmd.py``
assumes), and peak-buffer counts are measured off the simulated timeline
instead of asserted.

The instruction vocabulary (ForwardPass/SendActivation/…) keeps the
reference's names so training loops and tests can introspect schedules
through the same surface.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


# ---------------------------------------------------------------------------
# Instruction vocabulary
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PipeInstruction:
    """A single step command in a stage's instruction stream."""

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.__dict__.items())
        return f"{type(self).__name__}({args})"

    @property
    def name(self):
        return type(self).__name__


@dataclass(frozen=True, repr=False)
class OptimizerStep(PipeInstruction):
    pass


@dataclass(frozen=True, repr=False)
class ReduceGrads(PipeInstruction):
    pass


@dataclass(frozen=True, repr=False)
class ReduceTiedGrads(PipeInstruction):
    pass


@dataclass(frozen=True, repr=False)
class BufferOpInstruction(PipeInstruction):
    buffer_id: int = 0


@dataclass(frozen=True, repr=False)
class LoadMicroBatch(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class ForwardPass(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class BackwardPass(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class SendActivation(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class RecvActivation(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class SendGrad(BufferOpInstruction):
    pass


@dataclass(frozen=True, repr=False)
class RecvGrad(BufferOpInstruction):
    pass


# ---------------------------------------------------------------------------
# DAG simulation
# ---------------------------------------------------------------------------

_FWD = "F"
_BWD = "B"


@dataclass
class _Timeline:
    """Result of simulating the pipeline: per-stage, per-tick work items."""
    # work[s][t] = (kind, micro_batch) or None
    work: List[List[Optional[Tuple[str, int]]]]
    horizon: int
    peak_live: List[int]  # per-stage max concurrently-live microbatches


def _simulate(micro_batches: int, stages: int, with_backward: bool) -> _Timeline:
    """Greedy list-scheduling of the work DAG on `stages` sequential executors.

    Each tick, every stage runs at most one ready item. Readiness comes from
    the DAG (cross-stage deps finish one tick before use — the transfer hop);
    the policy is backward-first with the 1F1B live-microbatch bound.
    """
    done_at: Dict[Tuple[str, int, int], int] = {}  # (kind, m, s) -> tick
    live = [0] * stages
    peak = [0] * stages
    # 1F1B live-microbatch bound; meaningless without backwards to drain it
    # (forward-only output is consumed downstream immediately)
    limit = ([max(1, min(stages - s, micro_batches)) for s in range(stages)]
             if with_backward else [micro_batches] * stages)
    next_fwd = [0] * stages  # microbatches enter a stage in order
    next_bwd = [0] * stages
    work: List[List[Optional[Tuple[str, int]]]] = [[] for _ in range(stages)]

    total = micro_batches * stages * (2 if with_backward else 1)
    finished = 0
    t = 0
    while finished < total:
        picks: List[Optional[Tuple[str, int]]] = []
        for s in range(stages):
            pick = None
            # backward-first: drains live microbatches, bounding memory
            if with_backward and next_bwd[s] < micro_batches:
                m = next_bwd[s]
                own_fwd = done_at.get((_FWD, m, s))
                grad_in = (done_at.get((_BWD, m, s + 1))
                           if s + 1 < stages else own_fwd)
                if (own_fwd is not None and own_fwd < t
                        and grad_in is not None and grad_in < t):
                    pick = (_BWD, m)
            if pick is None and next_fwd[s] < micro_batches and live[s] < limit[s]:
                m = next_fwd[s]
                act_in = done_at.get((_FWD, m, s - 1)) if s > 0 else -1
                if act_in is not None and act_in < t:
                    pick = (_FWD, m)
            picks.append(pick)

        for s, pick in enumerate(picks):
            work[s].append(pick)
            if pick is None:
                continue
            kind, m = pick
            done_at[(kind, m, s)] = t
            finished += 1
            if kind == _FWD:
                next_fwd[s] += 1
                live[s] += 1
                peak[s] = max(peak[s], live[s])
            else:
                next_bwd[s] += 1
                live[s] -= 1
        t += 1
        assert t <= 4 * total + stages + 4, "scheduler wedged (DAG bug)"

    return _Timeline(work=work, horizon=t, peak_live=peak)


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------


class PipeSchedule:
    """Instruction streams for one stage, read off the simulated timeline."""

    _with_backward = True

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self._timeline = _simulate(micro_batches, stages, self._with_backward)

    # -- introspection ------------------------------------------------------
    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self):
        """Measured off the timeline: peak live microbatches, floor 2 (double
        buffering for the transfer hop)."""
        return max(2, self._timeline.peak_live[self.stage_id])

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    # -- stream generation --------------------------------------------------
    def steps(self):
        """Yield the per-tick instruction lists for this stage.

        Comm instructions are derived from the DAG edges: a cross-stage edge
        produces a Send in the producer's next tick and a Recv in the
        consumer's tick.
        """
        s = self.stage_id
        my_work = self._timeline.work[s]
        # sends scheduled into future ticks: tick -> [instruction]
        pending_sends: Dict[int, List[PipeInstruction]] = {}

        for t in range(self._timeline.horizon):
            cmds: List[PipeInstruction] = list(pending_sends.pop(t, ()))
            item = my_work[t] if t < len(my_work) else None
            if item is not None:
                kind, m = item
                buf = self._buffer_idx(m)
                if kind == _FWD:
                    if not self.is_first_stage:
                        cmds.append(RecvActivation(buf))
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(buf))
                    cmds.append(ForwardPass(buf))
                    if not self.is_last_stage:
                        pending_sends.setdefault(t + 1, []).append(SendActivation(buf))
                else:
                    if not self.is_last_stage:
                        cmds.append(RecvGrad(buf))
                    cmds.append(BackwardPass(buf))
                    if not self.is_first_stage:
                        pending_sends.setdefault(t + 1, []).append(SendGrad(buf))
            if self._with_backward and t == self._timeline.horizon - 1:
                cmds.extend([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
            yield cmds

    def __iter__(self):
        return self.steps()


class TrainSchedule(PipeSchedule):
    """1F1B: emergent from backward-first priority + the live-microbatch
    bound (reference capability: ``runtime/pipe/schedule.py`` TrainSchedule)."""
    _with_backward = True


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference capability: InferenceSchedule)."""
    _with_backward = False

    def num_pipe_buffers(self):
        return 2

    def _buffer_idx(self, micro_batch_id: int) -> int:
        # double-buffer: alternate so a send of batch m can overlap the
        # compute of batch m+1
        return micro_batch_id % 2


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: every microbatch is F then B on the
    one stage, optimizer at the end."""

    def __init__(self, micro_batches: int, stages: int = 1, stage_id: int = 0):
        # stages/stage_id preserved for introspection; steps() below is the
        # single-stage degenerate stream regardless
        super().__init__(micro_batches, stages, stage_id)

    def steps(self):
        for m in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if m == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
