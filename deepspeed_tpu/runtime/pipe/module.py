"""PipelineModule: layer-list model description + stage partitioning.

Rebuild of reference ``runtime/pipe/module.py`` (``LayerSpec :30``,
``TiedLayerSpec :77``, ``PipelineModule :86``, ``_partition_layers :391``):
the model is a flat list of layer builders; stages own contiguous slices
chosen by ``partition_method``:

- "uniform": equal layer counts
- "parameters": balance per-layer parameter counts
- "type:regex": balance layers whose class name matches the regex

TPU-native notes: layers build flax modules (or plain callables); `init`
returns per-layer param trees. For the SPMD fast path the homogeneous body
is *stacked* into [L, ...] leaves (`stack_params`) so stages hold [L/S, ...]
slices sharded over the ``pipe`` axis — one program, S stage shards. Tied
layers (word embedding reused at the head, reference ``module.py:444`` tied
allreduce) are realized by passing the same param subtree to both call
sites; the psum of the two gradient contributions is emitted by XLA.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class LayerSpec:
    """Deferred layer construction (reference module.py:30)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self, log=False):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared with every other layer of the same key
    (reference module.py:77)."""

    def __init__(self, key, typename, *module_args, forward_fn=None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


def _count_params(tree) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(tree)))


def partition_balanced(weights: Sequence[int], num_parts: int) -> List[int]:
    """Bounds [p0..p_num_parts] minimizing the max part weight over contiguous
    partitions (reference ds_utils.partition_balanced; DP over prefix sums)."""
    n = len(weights)
    prefix = np.concatenate([[0], np.cumsum(weights)])
    # binary search the optimal bottleneck, then greedy assignment
    lo, hi = max(weights) if weights else 0, int(prefix[-1])

    def parts_for(cap):
        bounds, start = [0], 0
        for _ in range(num_parts):
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= cap:
                end += 1
            bounds.append(end)
            start = end
        return bounds

    while lo < hi:
        mid = (lo + hi) // 2
        if parts_for(mid)[-1] >= n:
            hi = mid
        else:
            lo = mid + 1
    bounds = parts_for(lo)
    bounds[-1] = n
    # monotone fix for degenerate trailing parts
    for i in range(1, len(bounds)):
        bounds[i] = max(bounds[i], bounds[i - 1])
    return bounds


class PipelineModule:
    """Layer-list pipeline model (reference module.py:86).

    Not an nn.Module: it owns a list of built layers (flax modules or
    callables taking (params, x) / (x,)) plus partitioning metadata. The
    engine chooses the execution strategy; `__call__`-style sequential apply
    is provided for correctness checks and the non-pipelined fallback.
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 seed_layers: bool = False,
                 activation_checkpoint_interval: int = 0):
        self.layer_specs = list(layers)
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.tied_keys: Dict[str, List[int]] = {}

        self.layers = []
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                self.tied_keys.setdefault(spec.key, []).append(i)
                self.layers.append(spec.build())
            elif isinstance(spec, LayerSpec):
                self.layers.append(spec.build())
            else:
                self.layers.append(spec)  # already-built module/callable
        self._params = None
        self.parts = None

    # -------- init --------

    def init(self, rng, x):
        """Initialize per-layer params by threading a sample activation
        through the stack. Returns list of param trees (None for paramless
        layers); tied layers share one tree (first occurrence owns it)."""
        params = []
        tied_owner: Dict[str, Any] = {}
        for i, (spec, layer) in enumerate(zip(self.layer_specs, self.layers)):
            rng, sub = jax.random.split(rng)
            if hasattr(layer, "init"):  # flax module
                key = spec.key if isinstance(spec, TiedLayerSpec) else None
                if key is not None and key in tied_owner:
                    p = tied_owner[key]
                else:
                    p = layer.init({"params": sub}, x)
                    if key is not None:
                        tied_owner[key] = p
                params.append(p)
                x = layer.apply(p, x)
            else:
                params.append(None)
                x = layer(x)
        self._params = params
        return params

    # -------- partitioning (reference _partition_layers :391) --------

    def partition_layers(self, num_stages: Optional[int] = None) -> List[int]:
        num_stages = num_stages or self.num_stages
        n = len(self.layers)
        method = self.partition_method.lower()
        if method == "uniform":
            weights = [1] * n
        elif method == "parameters":
            assert self._params is not None, "call init() before parameters partitioning"
            weights = [max(_count_params(p), 1) if p is not None else 1 for p in self._params]
        elif method.startswith("type:"):
            pat = method.split(":", 1)[1]
            weights = [1 if re.search(pat, type(l).__name__, re.IGNORECASE) else 0
                       for l in self.layers]
            if sum(weights) == 0:
                weights = [1] * n
        else:
            raise NotImplementedError(f"Partitioning method {method} not implemented")
        self.parts = partition_balanced(weights, num_stages)
        return self.parts

    def stage_layers(self, stage_id: int) -> List:
        assert self.parts is not None, "call partition_layers() first"
        return self.layers[self.parts[stage_id]:self.parts[stage_id + 1]]

    # -------- sequential apply (correctness / fallback path) --------

    def apply(self, params_list, x, *loss_args):
        for layer, p in zip(self.layers, params_list):
            x = layer.apply(p, x) if p is not None else layer(x)
        if self.loss_fn is not None and loss_args:
            return self.loss_fn(x, *loss_args)
        return x

    # -------- SPMD stacking (homogeneous body) --------

    @staticmethod
    def stack_params(params_list):
        """Stack identical-structure per-layer trees into [L, ...] leaves —
        the layout the pipe axis shards (and lax.scan consumes)."""
        return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *params_list)
