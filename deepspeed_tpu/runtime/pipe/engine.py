"""PipelineEngine — training over the SPMD pipeline executor.

Rebuild of reference ``runtime/pipe/engine.py:61 PipelineEngine`` with the
same user contract — ``train_batch(data_iter)`` (:337) runs
gradient_accumulation_steps microbatches through the pipeline + one optimizer
step; ``eval_batch`` (:398) forward-only — but execution is the compiled
scan+ppermute pipeline (spmd.py), not a host instruction loop: under SPMD
the TrainSchedule's send/recv/fwd/bwd DAG is what XLA compiles the scan into.

Model structure: {embed, body, head}. Embed/head run replicated outside the
pipeline region (grads psum automatically); the homogeneous body is stacked
[L, ...] and sharded (L -> pipe axis, remaining dims by the ZeRO rule).
Composes with DP/fsdp: the batch stays sharded over the data axes — only the
``pipe`` axis is "manual" in the shard_map region.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.mesh import MeshContext
from ...utils.logging import logger
from ..zero_sharding import ZeroShardingPlan, composed_tp_zero_spec, leaf_spec
from ...parallel.tp import path_str
from .spmd import spmd_pipeline_1f1b, spmd_pipeline_eval

try:
    from jax import shard_map as _shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          axis_names={"pipe"}, check_vma=False)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=False, auto=frozenset(
                                  {"data", "fsdp", "seq", "expert", "model"}))


class PipeZeroPlan(ZeroShardingPlan):
    """ZeRO sharding with the pipe dimension consumed first: body leaves are
    [L, ...] with dim0 sharded over ``pipe``; the ZeRO rule — composed with
    TP when ``tp=True`` — applies to the remaining dims. The 1F1B executor's
    shard_map is partial-manual over ``pipe`` only, so model/zero sharding
    on the trailing dims stays GSPMD-managed inside the pipeline (psums on
    row-parallel weights land inside each stage)."""

    def __init__(self, ctx: MeshContext, stage: int, body_key: str = "body", **kw):
        super().__init__(ctx, stage, **kw)
        self.body_key = body_key

    def param_shardings(self, params):
        base = super().param_shardings(params)
        return self._override_body(params, base, self.stage >= 3,
                                   min_size=self.param_persistence_threshold)

    def grad_shardings(self, params):
        base = super().grad_shardings(params)
        return self._override_body(params, base, self.stage >= 2)

    def opt_state_shardings(self, opt_state, params=None):
        base = super().opt_state_shardings(opt_state)
        return self._override_body(opt_state, base, self.stage >= 1)

    def _override_body(self, tree, base, zero_active, min_size: int = 0):
        pipe = self.ctx.axis_size("pipe")
        if pipe <= 1:
            return base
        def _one(path, leaf, cur):
            names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
            shape = getattr(leaf, "shape", ())
            if self.body_key not in names or len(shape) == 0 or shape[0] % pipe != 0:
                return cur
            zaxes = self.zero_axes if (zero_active and self.zero_axes) else ()
            if self.tp:
                rest = composed_tp_zero_spec(
                    path_str(path), shape[1:], self.ctx, zaxes,
                    self.ctx.axis_size(zaxes) if zaxes else 1,
                    min_size=min_size)
            elif zaxes:
                rest = leaf_spec(shape[1:], zaxes,
                                 self.ctx.axis_size(zaxes), min_size=min_size)
            else:
                rest = P()
            return NamedSharding(self.ctx.mesh, P("pipe", *tuple(rest)))

        return jax.tree_util.tree_map_with_path(_one, tree, base)


def _zero_cotangent(x):
    """Cotangent for a non-differentiated input: float0 for int dtypes."""
    if jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.zeros_like(x)
    return np.zeros(x.shape, jax.dtypes.float0)


def pipe_compute_specs(tree, ctx: MeshContext, tp: bool, leading_pipe: bool):
    """Gather-for-compute shardings for the pre-pipeline constraint: the
    ZeRO axes are gathered ONCE per step (stage-3 semantics — collectives
    inside the scan's cond branches would also deadlock the CPU runtime's
    rendezvous), but under TP the model axis must STAY sharded — replicating
    it would silently defeat TP's compute/memory point every step.
    ``leading_pipe``: body leaves are [L, ...] with dim 0 on the pipe axis."""
    def _one(path, leaf):
        shape = getattr(leaf, "shape", ())
        lead = ("pipe", ) if leading_pipe and len(shape) > 0 else ()
        rest_shape = shape[1:] if lead else shape
        if tp:
            rest = tuple(composed_tp_zero_spec(path_str(path), rest_shape,
                                               ctx, (), 1))
        else:
            rest = ()
        return NamedSharding(ctx.mesh, P(*lead, *rest))

    return jax.tree_util.tree_map_with_path(_one, tree)


def make_pipeline_apply(embed_apply: Callable,
                        layer_apply: Callable,
                        head_apply: Callable,
                        mesh_ctx: MeshContext,
                        num_microbatches: int,
                        remat_layers: bool = True,
                        tp: bool = False):
    """Build an `apply_fn(params, *batch) -> loss` running {embed -> pipelined
    body -> head}. `params` = {"embed", "body" ([L,...] stacked), "head"}.

    - embed_apply(embed_params, *batch_inputs) -> [B, ...] activations
    - layer_apply(layer_params, x) -> x   (one body layer)
    - head_apply(head_params, x, *batch_targets) -> scalar loss
    The batch is split as inputs = batch[:-1], targets = batch[-1:].

    Training lowers to the interleaved 1F1B executor (embed inside stage 0,
    head inside the last stage — O(S·mb) activation memory); the loss's VJP
    returns the gradients the executor accumulated in-scan. Forward-only
    calls (eval) use the cheap InferenceSchedule executor.

    Loss semantics under pipe>1: the MEAN of per-microbatch head losses
    (reference pipe/engine.py:582 _aggregate_total_loss averages micro
    losses the same way). A head that masks tokens non-uniformly across
    microbatches yields mean-of-means, not a global token mean.
    """
    pipe = mesh_ctx.axis_size("pipe")
    mesh = mesh_ctx.mesh

    def stage_fn(stage_params, x):
        def one_layer(h, lp):
            f = layer_apply
            if remat_layers:
                f = jax.checkpoint(layer_apply)
            return f(lp, h), None

        out, _ = jax.lax.scan(one_layer, x, stage_params)
        return out

    # executor adapters: inputs/targets travel as tuples of microbatched arrays
    def ingest_fn(embed_params, in_mb):
        return embed_apply(embed_params, *in_mb)

    def head_fn(head_params, y, tgt_mb):
        return head_apply(head_params, y, *tgt_mb)

    body_specs = P("pipe")

    def run_train(body, embed, head, in_mbs, tgt_mbs):
        f = _smap(
            lambda b, e, hd, i, tg: spmd_pipeline_1f1b(
                stage_fn, ingest_fn, head_fn, b, e, hd, i, tg, axis_name="pipe"),
            mesh, (body_specs, P(), P(), P(), P()),
            (P(), body_specs, P(), P()))
        return f(body, embed, head, in_mbs, tgt_mbs)

    def run_eval(body, embed, head, in_mbs, tgt_mbs):
        f = _smap(
            lambda b, e, hd, i, tg: spmd_pipeline_eval(
                stage_fn, ingest_fn, head_fn, b, e, hd, i, tg, axis_name="pipe"),
            mesh, (body_specs, P(), P(), P(), P()), P())
        return f(body, embed, head, in_mbs, tgt_mbs)

    @jax.custom_vjp
    def pipelined(body, embed, head, in_mbs, tgt_mbs):
        return run_eval(body, embed, head, in_mbs, tgt_mbs)

    def pipelined_fwd(body, embed, head, in_mbs, tgt_mbs):
        loss, db, de, dh = run_train(body, embed, head, in_mbs, tgt_mbs)
        cast = lambda g, p: jax.tree_util.tree_map(  # noqa: E731
            lambda gg, pp: gg.astype(pp.dtype), g, p)
        return loss, (cast(db, body), cast(de, embed), cast(dh, head),
                      in_mbs, tgt_mbs)

    def pipelined_bwd(res, g):
        db, de, dh, in_mbs, tgt_mbs = res
        sc = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x * g.astype(x.dtype), tree)
        z = lambda tree: jax.tree_util.tree_map(_zero_cotangent, tree)  # noqa: E731
        return sc(db), sc(de), sc(dh), z(in_mbs), z(tgt_mbs)

    pipelined.defvjp(pipelined_fwd, pipelined_bwd)

    def _microbatch(tree, M):
        def one(x):
            B = x.shape[0]
            assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
            return x.reshape(M, B // M, *x.shape[1:])
        return jax.tree_util.tree_map(one, tree)

    def apply_fn(params, *batch):
        inputs, targets = batch[:-1], batch[-1:]
        M = num_microbatches
        if pipe > 1:
            in_mbs = _microbatch(tuple(inputs), M)
            tgt_mbs = _microbatch(tuple(targets), M)
            # ZeRO-3 x PP: gather params over the ZeRO axis ONCE per step,
            # OUTSIDE the pipeline scan (gather-for-compute, shard-at-rest —
            # stage3 semantics); under TP the model axis stays sharded
            # (pipe_compute_specs) — the partial-manual executor carries it
            body = jax.lax.with_sharding_constraint(
                params["body"],
                pipe_compute_specs(params["body"], mesh_ctx, tp, True))
            embed = jax.lax.with_sharding_constraint(
                params["embed"],
                pipe_compute_specs(params["embed"], mesh_ctx, tp, False))
            head = jax.lax.with_sharding_constraint(
                params["head"],
                pipe_compute_specs(params["head"], mesh_ctx, tp, False))
            return pipelined(body, embed, head, in_mbs, tgt_mbs)
        # pipe=1: plain sequential execution (no pipeline region)
        h = embed_apply(params["embed"], *inputs)
        mbs = _microbatch(h, M)
        out = jax.vmap(lambda x: stage_fn(params["body"], x))(mbs)
        out = out.reshape(h.shape[0], *out.shape[2:])
        return head_apply(params["head"], out, *targets)

    return apply_fn


class PipelineEngine:
    """Thin orchestrator with the reference train_batch/eval_batch surface.

    Delegates optimizer/checkpoint/precision to DeepSpeedTpuEngine by
    constructing it with the pipelined apply_fn and a PipeZeroPlan.
    """

    def __init__(self,
                 embed_apply: Callable,
                 layer_apply: Callable,
                 head_apply: Callable,
                 params,
                 config=None,
                 num_microbatches: Optional[int] = None):
        from ..engine import DeepSpeedTpuEngine

        assert set(params.keys()) >= {"embed", "body", "head"}, \
            "pipeline params must be {embed, body, head}"

        cfg = dict(config or {})
        gas = cfg.get("gradient_accumulation_steps", 1)

        class _Eng(DeepSpeedTpuEngine):
            def __init__(eng, **kw):
                super().__init__(**kw)

        # engine builds the mesh; apply_fn needs it — two-phase: create
        # engine with a placeholder then swap in the pipelined apply
        self._num_microbatches = num_microbatches
        self.engine = _Eng(model=lambda p, *a, **k: jnp.float32(0.0),
                           model_parameters=params, config=cfg, dont_shard=True)
        mesh_ctx = self.engine.mesh_ctx
        mb = num_microbatches or mesh_ctx.axis_size("pipe") * 2
        apply_fn = make_pipeline_apply(embed_apply, layer_apply, head_apply,
                                       mesh_ctx, mb,
                                       tp=getattr(self.engine, "_tp_training",
                                                  False))
        self.engine.apply_fn = apply_fn
        self.engine.zero_plan = PipeZeroPlan(
            mesh_ctx, self.engine._config.zero_config.stage,
            tp=getattr(self.engine, "_tp_training", False),
            param_persistence_threshold=(
                self.engine._config.zero_config.param_persistence_threshold))
        self.engine._init_state(params)
        self.engine._build_compiled_fns()
        self.micro_batches = mb

    def train_batch(self, data_iter):
        """One full batch: forward+backward over all microbatches (inside the
        compiled pipeline), then step (reference pipe/engine.py:337)."""
        batch = next(data_iter)
        if not isinstance(batch, (tuple, list)):
            batch = (batch, )
        loss = self.engine.forward(*batch)
        self.engine.backward(loss)
        self.engine.step()
        return loss

    def eval_batch(self, data_iter):
        batch = next(data_iter)
        if not isinstance(batch, (tuple, list)):
            batch = (batch, )
        return self.engine.eval_batch(*batch)

    def __getattr__(self, name):
        return getattr(self.engine, name)
