"""PipelineEngine — training over the SPMD pipeline executor.

Rebuild of reference ``runtime/pipe/engine.py:61 PipelineEngine`` with the
same user contract — ``train_batch(data_iter)`` (:337) runs
gradient_accumulation_steps microbatches through the pipeline + one optimizer
step; ``eval_batch`` (:398) forward-only — but execution is the compiled
scan+ppermute pipeline (spmd.py), not a host instruction loop: under SPMD
the TrainSchedule's send/recv/fwd/bwd DAG is what XLA compiles the scan into.

Model structure: {embed, body, head}. Embed/head run replicated outside the
pipeline region (grads psum automatically); the homogeneous body is stacked
[L, ...] and sharded (L -> pipe axis, remaining dims by the ZeRO rule).
Composes with DP/fsdp: the batch stays sharded over the data axes — only the
``pipe`` axis is "manual" in the shard_map region.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm.mesh import MeshContext
from ..zero_sharding import ZeroShardingPlan, leaf_spec
from .spmd import spmd_pipeline

try:
    from jax import shard_map as _shard_map

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          axis_names={"pipe"}, check_vma=False)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _smap(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=False, auto=frozenset(
                                  {"data", "fsdp", "seq", "expert", "model"}))


class PipeZeroPlan(ZeroShardingPlan):
    """ZeRO sharding with the pipe dimension consumed first: body leaves are
    [L, ...] with dim0 sharded over ``pipe``; the ZeRO rule applies to the
    remaining dims."""

    def __init__(self, ctx: MeshContext, stage: int, body_key: str = "body", **kw):
        super().__init__(ctx, stage, **kw)
        self.body_key = body_key

    def param_shardings(self, params):
        base = super().param_shardings(params)
        return self._override_body(params, base, self.stage >= 3)

    def grad_shardings(self, params):
        base = super().grad_shardings(params)
        return self._override_body(params, base, self.stage >= 2)

    def opt_state_shardings(self, opt_state, params=None):
        base = super().opt_state_shardings(opt_state)
        return self._override_body(opt_state, base, self.stage >= 1)

    def _override_body(self, tree, base, zero_active):
        pipe = self.ctx.axis_size("pipe")
        if pipe <= 1:
            return base

        def _one(path, leaf, cur):
            names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
            shape = getattr(leaf, "shape", ())
            if self.body_key not in names or len(shape) == 0 or shape[0] % pipe != 0:
                return cur
            rest = P()
            if zero_active and self.zero_axes:
                rest = leaf_spec(shape[1:], self.zero_axes, self.ctx.axis_size(self.zero_axes))
            return NamedSharding(self.ctx.mesh, P("pipe", *tuple(rest)))

        return jax.tree_util.tree_map_with_path(_one, tree, base)


def make_pipeline_apply(embed_apply: Callable,
                        layer_apply: Callable,
                        head_apply: Callable,
                        mesh_ctx: MeshContext,
                        num_microbatches: int,
                        remat_layers: bool = True):
    """Build an `apply_fn(params, *batch) -> loss` running {embed -> pipelined
    body -> head}. `params` = {"embed", "body" ([L,...] stacked), "head"}.

    - embed_apply(embed_params, *batch_inputs) -> [B, ...] activations
    - layer_apply(layer_params, x) -> x   (one body layer)
    - head_apply(head_params, x, *batch_targets) -> scalar loss
    The batch is split as inputs = batch[:-1], targets = batch[-1:].
    """
    pipe = mesh_ctx.axis_size("pipe")
    mesh = mesh_ctx.mesh

    def stage_fn(stage_params, x):
        def one_layer(h, lp):
            f = layer_apply
            if remat_layers:
                f = jax.checkpoint(layer_apply)
            return f(lp, h), None

        out, _ = jax.lax.scan(one_layer, x, stage_params)
        return out

    def apply_fn(params, *batch):
        inputs, targets = batch[:-1], batch[-1:]
        h = embed_apply(params["embed"], *inputs)  # [B, s, d]
        B = h.shape[0]
        M = num_microbatches
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mbs = h.reshape(M, B // M, *h.shape[1:])

        if pipe > 1:
            body_specs = jax.tree_util.tree_map(lambda _: P("pipe"), params["body"])
            run = _smap(
                lambda bp, xs: spmd_pipeline(stage_fn, bp, xs, axis_name="pipe"),
                mesh, (body_specs, P()), P())
            out = run(params["body"], mbs)
        else:
            out = jax.vmap(lambda x: stage_fn(params["body"], x))(mbs)

        out = out.reshape(B, *out.shape[2:])
        return head_apply(params["head"], out, *targets)

    return apply_fn


class PipelineEngine:
    """Thin orchestrator with the reference train_batch/eval_batch surface.

    Delegates optimizer/checkpoint/precision to DeepSpeedTpuEngine by
    constructing it with the pipelined apply_fn and a PipeZeroPlan.
    """

    def __init__(self,
                 embed_apply: Callable,
                 layer_apply: Callable,
                 head_apply: Callable,
                 params,
                 config=None,
                 num_microbatches: Optional[int] = None):
        from ..engine import DeepSpeedTpuEngine

        assert set(params.keys()) >= {"embed", "body", "head"}, \
            "pipeline params must be {embed, body, head}"

        cfg = dict(config or {})
        gas = cfg.get("gradient_accumulation_steps", 1)

        class _Eng(DeepSpeedTpuEngine):
            def __init__(eng, **kw):
                super().__init__(**kw)

        # engine builds the mesh; apply_fn needs it — two-phase: create
        # engine with a placeholder then swap in the pipelined apply
        self._num_microbatches = num_microbatches
        self.engine = _Eng(model=lambda p, *a, **k: jnp.float32(0.0),
                           model_parameters=params, config=cfg, dont_shard=True)
        mesh_ctx = self.engine.mesh_ctx
        mb = num_microbatches or mesh_ctx.axis_size("pipe") * 2
        apply_fn = make_pipeline_apply(embed_apply, layer_apply, head_apply,
                                       mesh_ctx, mb)
        self.engine.apply_fn = apply_fn
        self.engine.zero_plan = PipeZeroPlan(mesh_ctx, self.engine._config.zero_config.stage)
        self.engine._init_state(params)
        self.engine._build_compiled_fns()
        self.micro_batches = mb

    def train_batch(self, data_iter):
        """One full batch: forward+backward over all microbatches (inside the
        compiled pipeline), then step (reference pipe/engine.py:337)."""
        batch = next(data_iter)
        if not isinstance(batch, (tuple, list)):
            batch = (batch, )
        loss = self.engine.forward(*batch)
        self.engine.backward(loss)
        self.engine.step()
        return loss

    def eval_batch(self, data_iter):
        batch = next(data_iter)
        if not isinstance(batch, (tuple, list)):
            batch = (batch, )
        return self.engine.eval_batch(*batch)

    def __getattr__(self, name):
        return getattr(self.engine, name)
