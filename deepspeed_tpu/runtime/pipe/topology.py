"""Process topology — axis bookkeeping.

Rebuild of reference ``runtime/pipe/topology.py`` (``ProcessTopology :12``,
``PipeDataParallelTopology :244``): maps linear ranks <-> named axis
coordinates. On TPU the device mesh already IS this object; these classes
keep the reference API for code that reasons about coordinates (layer
partitioning, checkpoint naming, grid tests).
"""

import itertools
from collections import namedtuple
from typing import Dict, List, Sequence


class ProcessTopology:
    """Cartesian product of named axes; rank = row-major coordinate index."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(itertools.product(*ranges)):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        key = self.ProcessCoord(**coord_kwargs)
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", ), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis) -> List[List[int]]:
        """Lists of ranks that vary only along `axis` (the reference's
        process-group construction; on TPU: mesh-axis subsets)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in itertools.product(*ranges):
            other = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i, **other}) for i in range(self.get_dim(axis))]
            if len(ranks) > 1:
                lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return [rank for coord, rank in self.mapping.items() if _match(coord)]

    def get_axis_list(self, axis, idx) -> List[int]:
        return [rank for coord, rank in self.mapping.items() if getattr(coord, axis) == idx]

    @property
    def world_size(self):
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """pipe x data (reference :244)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe x data x model (reference :251)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])
