"""Pipeline parallelism.

TPU-native rebuild of reference ``deepspeed/runtime/pipe/``:
- ``LayerSpec``/``TiedLayerSpec``/``PipelineModule`` (module.py) — layer-list
  model description + stage partitioning
- instruction schedules (schedule.py) — TrainSchedule/InferenceSchedule
  (ported semantics; on TPU they describe, rather than drive, execution)
- the SPMD executor (spmd.py) — scan-over-ticks + ppermute over the ``pipe``
  mesh axis; reverse-mode autodiff of the scan IS the backward schedule
- ``PipelineEngine`` (engine.py) — train_batch/eval_batch over the executor
"""

from .module import LayerSpec, TiedLayerSpec, PipelineModule
from .schedule import (TrainSchedule, InferenceSchedule, DataParallelSchedule,
                       ForwardPass, BackwardPass, SendActivation, RecvActivation,
                       SendGrad, RecvGrad, LoadMicroBatch, ReduceGrads, ReduceTiedGrads,
                       OptimizerStep, PipeInstruction)
from .spmd import spmd_pipeline
from .engine import PipelineEngine, PipeZeroPlan, make_pipeline_apply
from .topology import PipeDataParallelTopology, PipeModelDataParallelTopology, ProcessTopology

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule", "spmd_pipeline",
    "PipelineEngine", "PipeZeroPlan", "make_pipeline_apply",
    "TrainSchedule", "InferenceSchedule", "DataParallelSchedule", "PipeInstruction",
    "ForwardPass", "BackwardPass", "SendActivation", "RecvActivation", "SendGrad",
    "RecvGrad", "LoadMicroBatch", "ReduceGrads", "ReduceTiedGrads", "OptimizerStep",
    "ProcessTopology", "PipeDataParallelTopology", "PipeModelDataParallelTopology",
]
