from . import checkpointing
