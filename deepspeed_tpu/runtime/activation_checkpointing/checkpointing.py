"""Activation checkpointing (Megatron-compatible API surface).

Reference: ``runtime/activation_checkpointing/checkpointing.py`` —
``checkpoint() :993`` / ``CheckpointFunction :486`` (autograd recompute),
``partition_activations :375`` (shard saved activations across TP ranks),
CPU checkpointing (host offload of saved activations), contiguous buffers,
``CudaRNGStatesTracker :124`` (fork RNG so dropout is consistent between the
forward and the recomputed forward).

TPU mapping:
- recompute = ``jax.checkpoint`` (jax.remat): policy-driven, composable with
  scan-over-layers; CheckpointFunction's saved-tensor plumbing is the AD
  system's job.
- partition_activations = saving residuals *sharded over the model axis*:
  achieved by a with_sharding_constraint on the checkpointed function's
  inputs — under GSPMD each rank then materializes only its slice of the
  saved activation (same memory win as the reference's explicit
  scatter/gather, no manual all_gather on backward: XLA inserts it).
- cpu_checkpointing = ``save_and_offload_only_these_names`` host offload
  when the jax version provides it; otherwise falls back to full recompute
  (strictly less memory than saving on device).
- RNG tracker: explicit key bookkeeping (JAX RNG is functional — the
  fork/restore dance reduces to reusing the same key for both executions,
  which jax.checkpoint does by construction; the tracker exists for
  Megatron-style callers that manage named dropout streams).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ...utils.logging import logger

_CONFIG = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": None,  # jax.checkpoint_policies name, e.g. "dots_saveable"
}

_MODEL_PARALLEL_RNG_KEY = "model-parallel-rng"


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference checkpointing.py:configure — store the knobs."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing_config", None)
        if ac is not None:
            _CONFIG["partition_activations"] = getattr(ac, "partition_activations", False)
            _CONFIG["cpu_checkpointing"] = getattr(ac, "cpu_checkpointing", False)
            _CONFIG["contiguous_memory_optimization"] = \
                getattr(ac, "contiguous_memory_optimization", False)
            _CONFIG["number_checkpoints"] = getattr(ac, "number_checkpoints", None)
            _CONFIG["policy"] = getattr(ac, "remat_policy", None)
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile)):
        if val is not None:
            _CONFIG[key] = val


def is_configured() -> bool:
    return True


def _resolve_policy():
    name = _CONFIG["policy"]
    if _CONFIG["cpu_checkpointing"]:
        # host-offload the saved residuals when this jax exposes it
        offload = getattr(jax.checkpoint_policies, "save_and_offload_only_these_names",
                          None)
        if offload is None:
            logger.warning("cpu_checkpointing: offload policy unavailable; "
                           "falling back to full recompute")
            return jax.checkpoint_policies.nothing_saveable
        return offload(names_which_can_be_saved=[], names_which_can_be_offloaded=[],
                       offload_src="device", offload_dst="pinned_host")
    if name:
        pol = getattr(jax.checkpoint_policies, name, None)
        if pol is None:
            raise ValueError(f"unknown remat policy '{name}'")
        return pol
    return None  # jax default: nothing saveable (full recompute)


def _partition_arg(x):
    """Shard a saved activation over the model axis (reference
    partition_activations :375: each TP rank keeps 1/mp of the tensor)."""
    from ...comm.mesh import get_mesh_context, mesh_is_initialized
    if not mesh_is_initialized() or not hasattr(x, "ndim") or x.ndim == 0:
        return x
    ctx = get_mesh_context()
    mp = ctx.mp_size
    if mp <= 1:
        return x
    # constrain the last axis (feature dim) over 'model' when divisible
    if x.shape[-1] % mp == 0:
        from jax.sharding import PartitionSpec as P
        spec = P(*([None] * (x.ndim - 1) + ["model"]))
        return jax.lax.with_sharding_constraint(x, ctx.sharding(*spec))
    return x


def checkpoint(function: Callable, *args, **kwargs):
    """Reference checkpoint() :993 — run `function` under remat; activations
    are recomputed in backward rather than saved."""
    policy = _resolve_policy()
    fn = function
    if _CONFIG["partition_activations"]:
        inner = function

        def fn(*a, **kw):  # noqa: F811 — saved inputs get model-axis sharding
            a = tuple(_partition_arg(x) for x in a)
            return inner(*a, **kw)

    return jax.checkpoint(fn, policy=policy)(*args, **kwargs)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form (used by models to remat per layer)."""

    def wrapped(*args, **kwargs):
        return checkpoint(function, *args, **kwargs)

    return wrapped


# ----------------------------------------------------------- RNG tracking

class RNGStatesTracker:
    """Reference CudaRNGStatesTracker :124 — named independent RNG streams.
    JAX keys are explicit, so a "state" is just a key; fork() yields a
    subkey deterministically, and the same key reaches both the forward and
    the remat recompute by construction."""

    def __init__(self):
        self._states = {}

    def reset(self):
        self._states.clear()

    def get_states(self):
        return dict(self._states)

    def set_states(self, states):
        self._states = dict(states)

    def add(self, name: str, seed: int):
        if name in self._states:
            raise Exception(f"RNG state {name} already exists")
        self._states[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = _MODEL_PARALLEL_RNG_KEY):
        """Context-manager-free fork: returns a fresh subkey and advances the
        stream (the torch version is a context manager because CUDA RNG is
        implicit global state; JAX has no such thing)."""
        if name not in self._states:
            raise Exception(f"RNG state {name} not added")
        self._states[name], sub = jax.random.split(self._states[name])
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:  # reference-compatible name
    return _RNG_TRACKER


def model_parallel_rng_seed(seed: int):
    """Reference model_parallel_cuda_manual_seed: data-parallel-identical,
    model-parallel-distinct streams. Returns (replicated_key, per-mp-rank
    key maker for use inside shard_map)."""
    base = jax.random.PRNGKey(seed)
    _RNG_TRACKER.reset()
    _RNG_TRACKER.set_states({_MODEL_PARALLEL_RNG_KEY: jax.random.fold_in(base, 2718)})

    def mp_key():
        # inside shard_map/jit: fold in this rank's model-axis index
        return jax.random.fold_in(base, jax.lax.axis_index("model") + 2718)

    return base, mp_key
