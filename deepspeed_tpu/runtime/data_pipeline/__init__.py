"""Data efficiency pipeline.

Rebuild of reference ``deepspeed/runtime/data_pipeline/``: curriculum
learning scheduler, difficulty-based data sampling, Megatron-format indexed
datasets, and random-LTD token dropping.
"""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder
from .data_routing import RandomLayerTokenDrop, RandomLTDScheduler

__all__ = [
    "CurriculumScheduler", "DeepSpeedDataSampler",
    "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
    "RandomLayerTokenDrop", "RandomLTDScheduler",
]
