"""Offline dataset difficulty analysis for curriculum learning.

Reference: ``runtime/data_pipeline/data_sampling/data_analyzer.py:22
DataAnalyzer`` — a map/reduce job computing per-sample metrics (seqlen,
vocab rarity, ...) over the whole dataset, writing indexed metric files the
curriculum sampler consumes. The reference shards work across
workers×threads with file-based merge; here the map is a multiprocessing
pool over index ranges and the reduce is in-memory numpy (a TPU-VM host
comfortably holds billions of int32 metric values), with the same output
artifacts: ``{metric}_sample_to_metric`` (per-sample value) and
``{metric}_metric_to_sample`` (value → sample ids) plus percentile stats.
"""

import json
import os
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...utils.logging import logger


def metric_seqlen(sample) -> int:
    """Built-in metric (reference analyzer's seqlen example)."""
    return int(np.asarray(sample).reshape(-1).shape[0])


def metric_vocab_rarity(vocab_freq: np.ndarray) -> Callable:
    """Built-in metric factory: mean -log frequency of a sample's tokens."""

    def fn(sample) -> int:
        ids = np.asarray(sample).reshape(-1)
        rar = -np.log(np.maximum(vocab_freq[ids], 1e-12)).mean()
        return int(rar * 1e3)  # fixed-point, metric files are integer-typed

    return fn


class DataAnalyzer:

    def __init__(self,
                 dataset,
                 num_workers: int = 1,
                 metric_names: Optional[List[str]] = None,
                 metric_functions: Optional[List[Callable]] = None,
                 save_path: str = "./data_analysis",
                 metric_types: Optional[List[str]] = None,
                 batch_size: int = 1024):
        self.dataset = dataset
        self.num_workers = max(1, num_workers)
        self.metric_names = metric_names or ["seqlen"]
        self.metric_functions = metric_functions or [metric_seqlen]
        self.metric_types = metric_types or ["single_value_per_sample"] * len(self.metric_names)
        self.save_path = save_path
        self.batch_size = batch_size

    # ---- map (reference run_map) ----

    def _map_range(self, lo: int, hi: int) -> Dict[str, np.ndarray]:
        out = {name: np.empty(hi - lo, dtype=np.int64) for name in self.metric_names}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for name, fn in zip(self.metric_names, self.metric_functions):
                out[name][i - lo] = fn(sample)
        return out

    def run_map(self) -> Dict[str, np.ndarray]:
        n = len(self.dataset)
        chunks = np.linspace(0, n, self.num_workers + 1, dtype=int)
        if self.num_workers == 1:
            parts = [self._map_range(0, n)]
        else:
            with ProcessPoolExecutor(max_workers=self.num_workers) as pool:
                parts = list(pool.map(self._map_range, chunks[:-1], chunks[1:]))
        return {name: np.concatenate([p[name] for p in parts]) for name in self.metric_names}

    # ---- reduce (reference run_reduce / merge_map_results) ----

    def run_reduce(self, mapped: Dict[str, np.ndarray]) -> Dict[str, dict]:
        os.makedirs(self.save_path, exist_ok=True)
        results = {}
        for name in self.metric_names:
            vals = mapped[name]
            np.save(os.path.join(self.save_path, f"{name}_sample_to_metric.npy"), vals)
            order = np.argsort(vals, kind="stable")
            np.save(os.path.join(self.save_path, f"{name}_metric_to_sample.npy"), order)
            stats = {
                "num_samples": int(vals.size),
                "min": int(vals.min()), "max": int(vals.max()),
                "mean": float(vals.mean()),
                "percentiles": {str(p): int(np.percentile(vals, p))
                                for p in (1, 5, 25, 50, 75, 95, 99)},
            }
            with open(os.path.join(self.save_path, f"{name}_stats.json"), "w") as f:
                json.dump(stats, f, indent=2)
            results[name] = stats
            logger.info(f"data analysis '{name}': {stats['percentiles']}")
        return results

    def run_map_reduce(self, comm_group=None) -> Dict[str, dict]:
        """Reference run_map_reduce — the one-call entry."""
        return self.run_reduce(self.run_map())


def load_metric(save_path: str, metric_name: str) -> np.ndarray:
    """Per-sample metric values for DeepSpeedDataSampler's metric_values."""
    return np.load(os.path.join(save_path, f"{metric_name}_sample_to_metric.npy"))
