"""Offline dataset difficulty analysis for curriculum learning.

Reference: ``runtime/data_pipeline/data_sampling/data_analyzer.py:22
DataAnalyzer`` (file-based map/reduce: each worker writes partial metric
files, worker 0 merges — ``:455 DistributedDataAnalyzer`` does the same over
collectives) — computing per-sample metrics (seqlen, vocab rarity, ...) over
the whole dataset, writing indexed metric files the curriculum sampler
consumes.

Three execution shapes, same artifacts:

- ``DataAnalyzer(dataset)`` — one driver, in-process pool over index ranges.
- ``DataAnalyzer(dataset, num_workers=N, worker_id=k)`` — THIS process is
  shard k of N (one per host, any scheduler): ``run_map`` writes partial
  files, worker 0's ``run_reduce`` waits for all partials and merges them in
  worker order (the reference's ``merge_map_results`` file protocol).
- ``DistributedDataAnalyzer(dataset)`` — SPMD multi-process JAX: shards by
  ``jax.process_index()``, merges via a cross-process allgather, process 0
  writes.

Artifacts per metric: ``{metric}_sample_to_metric.npy`` (per-sample value),
``{metric}_metric_to_sample.npy`` (sample ids sorted by value),
``{metric}_stats.json`` — and for ``accumulate_value_over_samples`` metrics
``{metric}_accumulated.npy`` (elementwise sum over the dataset, e.g. vocab
frequency counts).
"""

import json
import os
import time
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from ...utils.logging import logger

SINGLE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"

# dtype.num -> dtype for the ACCUMULATE collective's descriptor exchange
# (every process must pad with the SAME dtype, including empty shards)
_DT_BY_NUM = {np.dtype(t).num: np.dtype(t)
              for t in (np.bool_, np.int8, np.int16, np.int32, np.int64,
                        np.uint8, np.uint16, np.uint32, np.uint64,
                        np.float16, np.float32, np.float64)}


def metric_seqlen(sample) -> int:
    """Built-in metric (reference analyzer's seqlen example)."""
    return int(np.asarray(sample).reshape(-1).shape[0])


def metric_vocab_rarity(vocab_freq: np.ndarray) -> Callable:
    """Built-in metric factory: mean -log frequency of a sample's tokens."""

    def fn(sample) -> int:
        ids = np.asarray(sample).reshape(-1)
        rar = -np.log(np.maximum(vocab_freq[ids], 1e-12)).mean()
        return int(rar * 1e3)  # fixed-point, metric files are integer-typed

    return fn


def metric_vocab_freq(vocab_size: int) -> Callable:
    """Built-in ACCUMULATE metric (reference curriculum recipe step 1):
    per-token occurrence counts, summed over the whole dataset."""

    def fn(sample) -> np.ndarray:
        ids = np.asarray(sample).reshape(-1)
        return np.bincount(ids, minlength=vocab_size).astype(np.int64)

    return fn


class DataAnalyzer:

    def __init__(self,
                 dataset,
                 num_workers: int = 1,
                 worker_id: Optional[int] = None,
                 metric_names: Optional[List[str]] = None,
                 metric_functions: Optional[List[Callable]] = None,
                 save_path: str = "./data_analysis",
                 metric_types: Optional[List[str]] = None,
                 batch_size: int = 1024,
                 merge_timeout: float = 600.0,
                 run_id: str = "0"):
        self.dataset = dataset
        self.num_workers = max(1, num_workers)
        # None: one driver fans out in-process. int: THIS process is one
        # shard of the reference's multi-worker file protocol.
        self.worker_id = worker_id
        self.metric_names = metric_names or ["seqlen"]
        self.metric_functions = metric_functions or [metric_seqlen]
        self.metric_types = metric_types or [SINGLE] * len(self.metric_names)
        for t in self.metric_types:
            if t not in (SINGLE, ACCUMULATE):
                raise ValueError(f"metric_type {t} not implemented")
        self.save_path = save_path
        self.batch_size = batch_size
        self.merge_timeout = merge_timeout
        # partial files and the done marker are scoped by run_id so a rerun
        # in the same save_path (new dataset, new metrics) can never merge a
        # previous run's stale partials or return its stale stats — pass a
        # fresh run_id per analysis job (all workers must agree on it)
        self.run_id = str(run_id)

    # ---- map (reference run_map) ----

    def _worker_range(self, k: int):
        chunks = np.linspace(0, len(self.dataset), self.num_workers + 1, dtype=int)
        return int(chunks[k]), int(chunks[k + 1])

    def _map_range(self, lo: int, hi: int) -> Dict[str, np.ndarray]:
        out = {}
        for name, fn, mtype in zip(self.metric_names, self.metric_functions,
                                   self.metric_types):
            if mtype == SINGLE:
                vals = np.empty(hi - lo, dtype=np.int64)
                for i in range(lo, hi):
                    vals[i - lo] = fn(self.dataset[i])
                out[name] = vals
            else:  # ACCUMULATE: elementwise sum of fn(sample) over the range
                acc = None
                for i in range(lo, hi):
                    v = np.asarray(fn(self.dataset[i]))
                    acc = v.copy() if acc is None else acc + v
                out[name] = acc if acc is not None else np.zeros(0, np.int64)
        return out

    def _partial_path(self, k: int, name: str) -> str:
        return os.path.join(self.save_path,
                            f"worker{k}_{name}_r{self.run_id}_partial.npy")

    def run_map(self) -> Dict[str, np.ndarray]:
        n = len(self.dataset)
        if self.worker_id is not None:
            lo, hi = self._worker_range(self.worker_id)
            part = self._map_range(lo, hi)
            os.makedirs(self.save_path, exist_ok=True)
            for name, vals in part.items():
                tmp = self._partial_path(self.worker_id, name) + ".tmp"
                with open(tmp, "wb") as f:  # np.save(path) would append .npy
                    np.save(f, vals)
                # atomic publish: the merger must never read a half-written file
                os.replace(tmp, self._partial_path(self.worker_id, name))
            return part
        # same shard boundaries as the worker-sharded/SPMD modes — the
        # bit-identical-artifacts invariant depends on one chunking formula
        ranges = [self._worker_range(k) for k in range(self.num_workers)]
        if self.num_workers == 1:
            parts = [self._map_range(0, n)]
        else:
            with ProcessPoolExecutor(max_workers=self.num_workers) as pool:
                parts = list(pool.map(self._map_range,
                                      [r[0] for r in ranges],
                                      [r[1] for r in ranges]))
        return self._merge_parts(parts)

    def _merge_parts(self, parts: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        out = {}
        for name, mtype in zip(self.metric_names, self.metric_types):
            arrs = [p[name] for p in parts if p[name].size]
            if mtype == SINGLE:
                out[name] = np.concatenate(arrs) if arrs else np.zeros(0, np.int64)
            else:
                out[name] = np.sum(arrs, axis=0) if arrs else np.zeros(0, np.int64)
        return out

    def _wait_for_partials(self) -> Dict[str, np.ndarray]:
        """Worker 0's merge barrier: poll for every worker's partial files
        (reference merge_map_results reads each worker's output in order)."""
        deadline = time.time() + self.merge_timeout
        needed = [(k, name) for k in range(self.num_workers)
                  for name in self.metric_names]
        while True:
            missing = [p for p in needed
                       if not os.path.exists(self._partial_path(*p))]
            if not missing:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"data analysis merge: missing partial files after "
                    f"{self.merge_timeout}s: "
                    + ", ".join(self._partial_path(*p) for p in missing[:4]))
            time.sleep(0.2)
        parts = [{name: np.load(self._partial_path(k, name))
                  for name in self.metric_names}
                 for k in range(self.num_workers)]
        return self._merge_parts(parts)

    # ---- reduce (reference run_reduce / merge_map_results) ----

    def run_reduce(self, mapped: Optional[Dict[str, np.ndarray]] = None
                   ) -> Dict[str, dict]:
        if mapped is None:
            mapped = self._wait_for_partials()
        os.makedirs(self.save_path, exist_ok=True)
        results = {}
        for name, mtype in zip(self.metric_names, self.metric_types):
            vals = mapped[name]
            if mtype == ACCUMULATE:
                np.save(os.path.join(self.save_path, f"{name}_accumulated.npy"),
                        vals)
                stats = {"size": int(vals.size), "sum": int(vals.sum()),
                         "nonzero": int(np.count_nonzero(vals))}
            else:
                np.save(os.path.join(self.save_path,
                                     f"{name}_sample_to_metric.npy"), vals)
                order = np.argsort(vals, kind="stable")
                np.save(os.path.join(self.save_path,
                                     f"{name}_metric_to_sample.npy"), order)
                stats = {
                    "num_samples": int(vals.size),
                    "min": int(vals.min()), "max": int(vals.max()),
                    "mean": float(vals.mean()),
                    "percentiles": {str(p): int(np.percentile(vals, p))
                                    for p in (1, 5, 25, 50, 75, 95, 99)},
                }
            with open(os.path.join(self.save_path, f"{name}_stats.json"), "w") as f:
                json.dump(stats, f, indent=2)
            results[name] = stats
            logger.info(f"data analysis '{name}': {stats}")
        done_tmp = os.path.join(self.save_path, "analysis_done.json.tmp")
        with open(done_tmp, "w") as f:
            json.dump({"metrics": self.metric_names, "run_id": self.run_id}, f)
        os.replace(done_tmp, os.path.join(self.save_path, "analysis_done.json"))
        return results

    def run_map_reduce(self, comm_group=None) -> Dict[str, dict]:
        """Reference run_map_reduce — the one-call entry. In worker-sharded
        mode every worker maps; worker 0 merges + writes; the rest wait for
        the done marker and load the published stats."""
        if self.worker_id is None:
            return self.run_reduce(self.run_map())
        self.run_map()
        done = os.path.join(self.save_path, "analysis_done.json")
        if self.worker_id == 0:
            return self.run_reduce()
        deadline = time.time() + self.merge_timeout

        def _published() -> bool:
            if not os.path.exists(done):
                return False
            try:  # a marker from an older run in the same dir is NOT done
                return json.load(open(done)).get("run_id") == self.run_id
            except (json.JSONDecodeError, OSError):
                return False

        while not _published():
            if time.time() > deadline:
                raise TimeoutError("worker 0 never published analysis_done.json "
                                   f"for run_id={self.run_id}")
            time.sleep(0.2)
        return {name: json.load(open(os.path.join(self.save_path,
                                                  f"{name}_stats.json")))
                for name in self.metric_names}


class DistributedDataAnalyzer:
    """SPMD analyzer (reference ``data_analyzer.py:455``): shards the dataset
    by JAX process, merges partial results with a cross-process allgather,
    process 0 writes the same artifacts as ``DataAnalyzer``."""

    def __init__(self, dataset, metric_names=None, metric_functions=None,
                 metric_types=None, save_path: str = "./data_analysis",
                 comm_group=None):
        import jax
        self.worker_id = jax.process_index()
        self.num_workers = jax.process_count()
        self._inner = DataAnalyzer(dataset, num_workers=self.num_workers,
                                   worker_id=self.worker_id,
                                   metric_names=metric_names,
                                   metric_functions=metric_functions,
                                   metric_types=metric_types,
                                   save_path=save_path)

    def run_map_reduce(self) -> Dict[str, dict]:
        import jax
        from jax.experimental import multihost_utils
        inner = self._inner
        lo, hi = inner._worker_range(self.worker_id)
        part = inner._map_range(lo, hi)
        # allgather each metric across processes; SINGLE ranges can be
        # uneven, so pad to the max range length and trim by true lengths
        merged = {}
        for name, mtype in zip(inner.metric_names, inner.metric_types):
            vals = part[name]
            if mtype == SINGLE:
                width = int(np.ceil(len(inner.dataset) / self.num_workers))
                padded = np.zeros(width, np.int64)
                padded[:vals.size] = vals
                gathered = np.asarray(multihost_utils.process_allgather(padded))
                gathered = gathered.reshape(self.num_workers, width)
                pieces = []
                for k in range(self.num_workers):
                    klo, khi = inner._worker_range(k)
                    pieces.append(gathered[k, :khi - klo])
                merged[name] = np.concatenate(pieces)
            else:
                # a process whose shard is EMPTY has a zero-size partial but
                # the collective needs identical shapes AND dtypes: exchange
                # (size, dtype enum) first, pad empties with zeros of the
                # dtype some non-empty peer reported
                desc = np.asarray([vals.size,
                                   np.dtype(vals.dtype).num if vals.size else -1],
                                  np.int64)
                descs = np.asarray(multihost_utils.process_allgather(desc))
                descs = descs.reshape(self.num_workers, 2)
                width = int(descs[:, 0].max())
                dt_nums = [int(d) for d in descs[:, 1] if d >= 0]
                if dt_nums and dt_nums[0] not in _DT_BY_NUM:
                    raise TypeError(
                        f"ACCUMULATE metric '{name}' uses an unsupported "
                        f"dtype (num={dt_nums[0]}); supported: "
                        f"{sorted(str(d) for d in _DT_BY_NUM.values())}")
                dt = _DT_BY_NUM[dt_nums[0]] if dt_nums else np.dtype(np.int64)
                padded = np.zeros(width, dt)
                padded[:vals.size] = vals
                gathered = np.asarray(multihost_utils.process_allgather(padded))
                merged[name] = gathered.reshape(self.num_workers, width).sum(axis=0)
        if self.worker_id == 0:
            results = inner.run_reduce(merged)
        else:
            results = {name: None for name in inner.metric_names}
        multihost_utils.sync_global_devices("data_analysis_reduce")
        return results


def load_metric(save_path: str, metric_name: str) -> np.ndarray:
    """Per-sample metric values for DeepSpeedDataSampler's metric_values."""
    return np.load(os.path.join(save_path, f"{metric_name}_sample_to_metric.npy"))


def load_accumulated(save_path: str, metric_name: str) -> np.ndarray:
    """Dataset-wide accumulated metric (e.g. vocab frequency counts)."""
    return np.load(os.path.join(save_path, f"{metric_name}_accumulated.npy"))
