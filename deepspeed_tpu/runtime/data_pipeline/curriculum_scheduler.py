"""Curriculum learning scheduler.

Rebuild of reference ``runtime/data_pipeline/curriculum_scheduler.py:11
CurriculumScheduler`` with the same JSON config keys and difficulty
schedules: fixed_linear, fixed_root, fixed_discrete, custom.
"""

import math
from typing import Callable, Dict, Optional

from ...utils.logging import logger

MIN_DIFFICULTY = "min_difficulty"
MAX_DIFFICULTY = "max_difficulty"
CURRENT_DIFFICULTY = "current_difficulty"
SCHEDULE_TYPE = "schedule_type"
SCHEDULE_CONFIG = "schedule_config"
SCHEDULE_FIXED_LINEAR = "fixed_linear"
SCHEDULE_FIXED_ROOT = "fixed_root"
SCHEDULE_FIXED_DISCRETE = "fixed_discrete"
SCHEDULE_CUSTOM = "custom"
TOTAL_STEP = "total_curriculum_step"
DIFFICULTY_STEP = "difficulty_step"
ROOT_DEGREE = "root_degree"
DIFFICULTY = "difficulty"
MAX_STEP = "max_step"


class CurriculumScheduler:

    def __init__(self, config: Dict):
        self.state = {}
        for key in (MIN_DIFFICULTY, MAX_DIFFICULTY, SCHEDULE_TYPE):
            assert key in config, f"Curriculum learning requires the config '{key}'"
        self.state[MIN_DIFFICULTY] = config[MIN_DIFFICULTY]
        self.state[MAX_DIFFICULTY] = config[MAX_DIFFICULTY]
        self.state[CURRENT_DIFFICULTY] = config[MIN_DIFFICULTY]
        self.state[SCHEDULE_TYPE] = config[SCHEDULE_TYPE]
        self.first_step = True
        self.custom_get_difficulty: Optional[Callable] = None

        stype = config[SCHEDULE_TYPE]
        sconf = config.get(SCHEDULE_CONFIG, {})
        if stype == SCHEDULE_FIXED_DISCRETE:
            assert DIFFICULTY in sconf and MAX_STEP in sconf
            assert len(sconf[DIFFICULTY]) == len(sconf[MAX_STEP]) + 1
            self.state[SCHEDULE_CONFIG] = sconf
        elif stype in (SCHEDULE_FIXED_ROOT, SCHEDULE_FIXED_LINEAR):
            assert TOTAL_STEP in sconf and DIFFICULTY_STEP in sconf
            if stype == SCHEDULE_FIXED_ROOT:
                assert ROOT_DEGREE in sconf
            if sconf[DIFFICULTY_STEP] % 8 != 0:
                logger.warning(
                    "difficulty_step not a multiple of 8; disregard if your metric "
                    "is unrelated to seqlen padding efficiency.")
            self.state[SCHEDULE_CONFIG] = sconf
        elif stype == SCHEDULE_CUSTOM:
            pass
        else:
            raise RuntimeError(f"Unsupported curriculum schedule type {stype}")

    # -------- queries --------

    def get_current_difficulty(self):
        return self.state[CURRENT_DIFFICULTY]

    def set_current_difficulty(self, difficulty):
        self.state[CURRENT_DIFFICULTY] = difficulty

    def set_custom_get_difficulty(self, schedule_function: Callable):
        self.custom_get_difficulty = schedule_function

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    # -------- schedules (reference :131-180) --------

    def __fixed_discrete_get_difficulty(self, global_steps):
        s = self.state[SCHEDULE_CONFIG]
        if global_steps > s[MAX_STEP][-1]:
            return s[DIFFICULTY][-1]
        for i, step in enumerate(s[MAX_STEP]):
            if global_steps <= step:
                return s[DIFFICULTY][i]
        return s[DIFFICULTY][-1]

    def __fixed_root_get_difficulty(self, global_steps, root_degree=None):
        s = self.state[SCHEDULE_CONFIG]
        if root_degree is None:
            root_degree = s[ROOT_DEGREE]
        frac = (float(global_steps) / s[TOTAL_STEP]) ** (1.0 / root_degree)
        next_difficulty = math.floor(
            frac * (self.state[MAX_DIFFICULTY] - self.state[MIN_DIFFICULTY])
            + self.state[MIN_DIFFICULTY])
        next_difficulty -= next_difficulty % s[DIFFICULTY_STEP]
        return min(next_difficulty, self.state[MAX_DIFFICULTY])

    def get_difficulty(self, global_steps):
        stype = self.state[SCHEDULE_TYPE]
        if stype == SCHEDULE_FIXED_DISCRETE:
            return self.__fixed_discrete_get_difficulty(global_steps)
        if stype == SCHEDULE_FIXED_LINEAR:
            return self.__fixed_root_get_difficulty(global_steps, 1)
        if stype == SCHEDULE_FIXED_ROOT:
            return self.__fixed_root_get_difficulty(global_steps)
        if stype == SCHEDULE_CUSTOM:
            assert self.custom_get_difficulty is not None, \
                "custom schedule requires set_custom_get_difficulty()"
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"Unsupported curriculum schedule type {stype}")

    def update_difficulty(self, global_steps):
        if self.state[CURRENT_DIFFICULTY] < self.state[MAX_DIFFICULTY]:
            self.state[CURRENT_DIFFICULTY] = self.get_difficulty(global_steps)
        return self.state[CURRENT_DIFFICULTY]
