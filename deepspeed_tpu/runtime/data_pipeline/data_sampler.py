"""Curriculum-aware distributed data sampler.

Rebuild of reference ``runtime/data_pipeline/data_sampling/data_sampler.py:36
DeepSpeedDataSampler``: deterministic epoch shuffling + per-dp-rank batch
index slices, with optional curriculum filtering — at each step, only samples
whose difficulty metric is <= the scheduler's current difficulty are
eligible. Difficulty metrics are plain arrays here (the reference reads them
from indexed metric files; `metric_values` accepts either an array or an
MMapIndexedDataset).
"""

from typing import Iterator, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:

    def __init__(self,
                 total_samples: int,
                 micro_batch_size: int,
                 data_parallel_rank: int = 0,
                 data_parallel_size: int = 1,
                 gradient_accumulation_steps: int = 1,
                 curriculum_scheduler: Optional[CurriculumScheduler] = None,
                 metric_values: Optional[Sequence] = None,
                 drop_last: bool = True,
                 shuffle: bool = True,
                 seed: int = 1234):
        self.total_samples = total_samples
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.global_batch_size = micro_batch_size * data_parallel_size * gradient_accumulation_steps
        self.curriculum = curriculum_scheduler
        self.metric_values = None if metric_values is None else np.asarray(metric_values)
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.consumed_samples = 0
        if self.curriculum is not None:
            assert self.metric_values is not None, \
                "curriculum sampling needs per-sample difficulty metrics"

    def __len__(self):
        n = self.total_samples
        if self.drop_last:
            return n // self.global_batch_size
        return (n + self.global_batch_size - 1) // self.global_batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def state_dict(self):
        return {"epoch": self.epoch, "consumed_samples": self.consumed_samples,
                "curriculum": None if self.curriculum is None else self.curriculum.get_state()}

    def load_state_dict(self, sd):
        self.epoch = sd["epoch"]
        self.consumed_samples = sd["consumed_samples"]
        if self.curriculum is not None and sd.get("curriculum"):
            self.curriculum.set_state(sd["curriculum"])

    def _epoch_order(self) -> np.ndarray:
        order = np.arange(self.total_samples)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self) -> Iterator[np.ndarray]:
        """Yields [micro_batch_size] index arrays for THIS dp rank."""
        order = self._epoch_order()
        step = self.consumed_samples // self.global_batch_size
        # resume mid-epoch: skip what this epoch already consumed
        epoch_samples = len(self) * self.global_batch_size if self.drop_last else self.total_samples
        pos = self.consumed_samples % epoch_samples if epoch_samples else 0
        while pos + self.global_batch_size <= len(order) or (
                not self.drop_last and pos < len(order)):
            if self.curriculum is not None:
                difficulty = self.curriculum.update_difficulty(step + 1)
                eligible = order[self.metric_values[order] <= difficulty]
                if len(eligible) < self.global_batch_size:
                    eligible = order  # degenerate config: fall back to all
                # deterministic draw keyed by step: full eligible-pool coverage
                # in expectation, and resume replays the same batch
                rng = np.random.default_rng([self.seed, self.epoch, step])
                batch = rng.choice(eligible, self.global_batch_size, replace=False)
            else:
                batch = order[pos:pos + self.global_batch_size]
            if len(batch) < self.global_batch_size and self.drop_last:
                break
            # slice this rank's micro-batches (contiguous per-rank layout)
            for g in range(self.gas):
                lo = g * self.micro_batch_size * self.dp_size + self.dp_rank * self.micro_batch_size
                mb = batch[lo:lo + self.micro_batch_size]
                if len(mb):
                    yield np.asarray(mb)
            pos += self.global_batch_size
            self.consumed_samples += self.global_batch_size
            step += 1
