"""Random layer token dropping (random-LTD).

Rebuild of reference ``runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop`` + its scheduler: wrap a transformer layer so only a
random subset of tokens passes through it (the rest bypass), with the kept
count annealed up to full length over training. The reference's CUDA
``token_sort``/``gather_scatter`` kernels are jnp argsort/take_along_axis —
XLA-native on TPU.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def random_ltd_select(rng, hidden, keep: int):
    """Pick `keep` random token indices per batch row; returns (sorted idx
    [B, keep], gathered hidden [B, keep, D])."""
    B, S = hidden.shape[0], hidden.shape[1]
    scores = jax.random.uniform(rng, (B, S))
    idx = jnp.argsort(scores, axis=1)[:, :keep]
    idx = jnp.sort(idx, axis=1)  # keep relative order (reference token_sort)
    gathered = jnp.take_along_axis(hidden, idx[..., None], axis=1)
    return idx, gathered


def random_ltd_scatter(hidden, processed, idx):
    """Scatter processed tokens back into the full sequence (bypass rest)."""
    return hidden.at[jnp.arange(hidden.shape[0])[:, None], idx].set(processed)


class RandomLayerTokenDrop:
    """Functional wrapper: layer_fn(params, x[, ...]) -> x applied to a random
    token subset of annealed size."""

    def __init__(self, layer_fn: Callable):
        self.layer_fn = layer_fn

    def __call__(self, params, hidden, keep: int, rng, *args, **kwargs):
        S = hidden.shape[1]
        if keep >= S:
            return self.layer_fn(params, hidden, *args, **kwargs)
        idx, sub = random_ltd_select(rng, hidden, keep)
        out = self.layer_fn(params, sub, *args, **kwargs)
        return random_ltd_scatter(hidden, out, idx)


class RandomLTDScheduler:
    """Kept-token schedule (reference ``scheduler.py RandomLTDScheduler``):
    linear anneal from `start_value` to `max_value` (full seqlen) over
    `total_layer_tokens` steps in increments of `step_size`."""

    def __init__(self, config: Dict):
        ltd = config.get("random_ltd", config)
        sched = ltd.get("random_ltd_schedule", ltd)
        self.start_value = sched.get("start_value", ltd.get("random_ltd_layer_num", 128))
        self.max_value = sched.get("max_value", 2048)
        self.step_size = sched.get("step_size", 16)
        self.schedule_steps = sched.get("schedule_steps", sched.get("total_layer_tokens", 1000))
        self.current_value = self.start_value
        self.global_step = 0

    def get_current_seq(self):
        return self.current_value

    def update_seq(self, global_step: int):
        self.global_step = global_step
        frac = min(global_step / max(self.schedule_steps, 1), 1.0)
        val = int(self.start_value + frac * (self.max_value - self.start_value))
        val -= val % self.step_size
        self.current_value = min(max(val, self.start_value), self.max_value)
        return self.current_value

    def state_dict(self):
        return {"current_value": self.current_value, "global_step": self.global_step}

    def load_state_dict(self, sd):
        self.current_value = sd["current_value"]
        self.global_step = sd["global_step"]
