"""Memory-mapped indexed dataset (Megatron .bin/.idx format).

Rebuild of reference ``runtime/data_pipeline/data_sampling/indexed_dataset.py:369
MMapIndexedDataset`` — same on-disk layout (magic ``MMIDIDX``, version, dtype
code, counts, sizes, pointers; raw sample data in the .bin) so datasets
preprocessed for Megatron/DeepSpeed load unchanged. Reads are zero-copy numpy
memmap views; the host dataloader hands them to ``jax.device_put``.
"""

import os
import struct
from typing import List, Sequence, Union

import numpy as np

_INDEX_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

_DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
    5: np.int64, 6: np.float64, 7: np.float32, 8: np.uint16,
}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDataset:

    class Index:

        def __init__(self, path):
            with open(path, "rb") as f:
                magic = f.read(9)
                assert magic == _INDEX_MAGIC, (
                    f"Index file {path} has bad magic — not an MMapIndexedDataset index")
                (version, ) = struct.unpack("<Q", f.read(8))
                assert version == _VERSION
                (dtype_code, ) = struct.unpack("<B", f.read(1))
                self.dtype = _DTYPES[dtype_code]
                (self._len, ) = struct.unpack("<Q", f.read(8))
                (self._doc_count, ) = struct.unpack("<Q", f.read(8))
                offset = f.tell()
            buf = np.memmap(path, mode="r", order="C")
            self.sizes = np.frombuffer(buf, dtype=np.int32, count=self._len, offset=offset)
            ptr_off = offset + self.sizes.nbytes
            self.pointers = np.frombuffer(buf, dtype=np.int64, count=self._len, offset=ptr_off)
            doc_off = ptr_off + self.pointers.nbytes
            self.doc_idx = np.frombuffer(buf, dtype=np.int64, count=self._doc_count,
                                         offset=doc_off)

        def __len__(self):
            return self._len

    def __init__(self, path_prefix: str, skip_warmup: bool = True):
        # skip_warmup kept for reference API parity only: the reference
        # optionally touch-reads the mmap to prime the page cache; host-side
        # np.memmap readahead makes that unnecessary here, so it's a no-op.
        self._path = path_prefix
        self._index = self.Index(index_file_path(path_prefix))
        self._bin = np.memmap(data_file_path(path_prefix), mode="r", order="C")

    def __len__(self):
        return len(self._index)

    @property
    def sizes(self):
        return self._index.sizes

    @property
    def doc_idx(self):
        return self._index.doc_idx

    def __getitem__(self, idx: Union[int, slice]):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        ptr = self._index.pointers[idx]
        size = self._index.sizes[idx]
        return np.frombuffer(self._bin, dtype=self._index.dtype, count=size, offset=ptr)

    def get(self, idx, offset=0, length=None):
        ptr = self._index.pointers[idx] + offset * np.dtype(self._index.dtype).itemsize
        size = self._index.sizes[idx] - offset
        if length is not None:
            size = min(size, length)
        return np.frombuffer(self._bin, dtype=self._index.dtype, count=size, offset=ptr)

    @staticmethod
    def exists(path_prefix):
        return (os.path.exists(index_file_path(path_prefix))
                and os.path.exists(data_file_path(path_prefix)))


class MMapIndexedDatasetBuilder:
    """Writer (reference ``indexed_dataset.py MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_file_prefix: str, dtype=np.int32):
        self._prefix = out_file_prefix
        self._dtype = np.dtype(dtype)
        self._bin = open(data_file_path(out_file_prefix), "wb")
        self._sizes: List[int] = []
        self._pointers: List[int] = []
        self._doc_idx: List[int] = [0]
        self._offset = 0

    def add_item(self, tensor: Sequence):
        arr = np.asarray(tensor, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._pointers.append(self._offset)
        self._sizes.append(arr.size)
        self._offset += arr.nbytes

    def end_document(self):
        self._doc_idx.append(len(self._sizes))

    def finalize(self):
        self._bin.close()
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_INDEX_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(np.asarray(self._sizes, dtype=np.int32).tobytes(order="C"))
            f.write(np.asarray(self._pointers, dtype=np.int64).tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, dtype=np.int64).tobytes(order="C"))
