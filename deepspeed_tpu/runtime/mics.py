"""MiCS — Minimize Communication Scale (ZeRO-3 in shard groups).

Reference: ``runtime/zero/mics.py:64 MiCS_Init`` / ``:362 MiCS_Optimizer``
/ ``_hierarchical_all_gather_params :254``: partition parameters within a
*shard group* (typically one node) and replicate across groups, so the hot
allgather rides intra-node links; a two-hop hierarchical gather covers the
cross-group hop.

TPU-native formulation: MiCS is pure mesh algebra. shard_size=S on N chips →
mesh {data: N/S, fsdp: S} with ZeRO-3 sharding over ``fsdp`` only (the
inner, ICI-contiguous axis) and replication over ``data``. XLA emits the
intra-group allgather on the fsdp axis; the "hierarchical gather" is the
partitioner's job — gradient reduction crosses groups via psum over data,
exactly the reference's allreduce-across-groups after local reduce-scatter.
Note this is the same mesh trick as ZeRO++ hpZ (``zeropp.hpz_mesh_axes``)
— the reference implements them as two different 2.9k-LoC optimizer
subclasses; here both are 10-line mesh planners.
"""

from typing import Dict

from ..utils.logging import logger


def mics_mesh_axes(n_devices: int, shard_size: int) -> Dict[str, int]:
    """Mesh axes for a MiCS shard-group size (reference MiCS_Init
    partition-group creation, mics.py:115)."""
    if shard_size <= 1:
        return {"data": -1}
    if shard_size > n_devices or n_devices % shard_size != 0:
        raise ValueError(f"mics_shard_size={shard_size} must divide the device "
                         f"count {n_devices}")
    return {"data": n_devices // shard_size, "fsdp": shard_size}


class MiCS_Init:
    """Context-manager shim (reference MiCS_Init subclasses zero.Init and
    monkey-patches module construction; under SPMD the engine just builds
    the mesh from mics_shard_size, so this records intent and validates)."""

    def __init__(self, shard_size: int, n_devices: int = None):
        import jax
        self.shard_size = shard_size
        self.axes = mics_mesh_axes(n_devices or jax.device_count(), shard_size)

    def __enter__(self):
        logger.info(f"MiCS: shard groups of {self.shard_size} -> mesh {self.axes}")
        return self

    def __exit__(self, *exc):
        return False
