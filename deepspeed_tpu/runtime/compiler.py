"""torch.compile API shim.

Reference: ``runtime/compiler.py`` + ``engine.py:3665 compile()`` — opt-in
graph compilation of the wrapped module. Under this framework everything is
ALREADY traced and XLA-compiled at first dispatch (the engine jits
fwd_bwd/apply as whole programs), so ``compile()`` only records the request —
but ``is_compiled`` keeps the reference's contract: False until ``compile()``
has been called, True afterwards."""

import os
from typing import Any, Callable, Optional

from ..utils.logging import logger


def is_compile_supported() -> bool:
    return True


def _reset_cache_latch() -> None:
    """jax's compilation-cache module latches a "disabled" state at the
    first compile that runs with no cache dir configured (model.init, eager
    ops before engine construction all count). After that latch, config
    updates are silently ignored — entries log "cache is disabled/not
    initialized". reset_cache() clears the latch so the NEXT compile
    re-initializes against the directory just configured."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover — internals moved; cache is best-effort
        pass


def configure_compile_cache(compile_config) -> Callable[[], None]:
    """Point JAX's persistent compilation cache at ``compile.cache_dir``
    (the autotuner's ``_enable_compile_cache`` promoted into engine init):
    multi-restart runs skip recompiles of the engine's step programs.

    A pre-existing ``JAX_COMPILATION_CACHE_DIR`` env var or jax.config
    setting always wins — the engine never redirects a cache the user (or a
    supervisor process) already chose. The env var is also SET here so
    spawned child processes inherit the cache. Returns an undo() restoring
    prior state (no-op when nothing was applied)."""
    path = getattr(compile_config, "cache_dir", None)
    if not path:
        return lambda: None
    import jax
    if (os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or getattr(jax.config, "jax_compilation_cache_dir", None)):
        return lambda: None  # user's cache wins
    path = str(path)
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    min_secs = getattr(compile_config, "cache_min_compile_secs", None)
    prev_min = getattr(jax.config,
                       "jax_persistent_cache_min_compile_time_secs", None)
    applied = False
    try:
        os.makedirs(path, exist_ok=True)
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
        jax.config.update("jax_compilation_cache_dir", path)
        if min_secs is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_secs))
        _reset_cache_latch()
        applied = True
    except Exception as e:  # pragma: no cover — the cache is an optimization
        logger.warning(f"persistent compile cache unavailable: {e}")

    def undo() -> None:
        if not applied:
            return
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        try:
            jax.config.update("jax_compilation_cache_dir", prev)
            if min_secs is not None:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", prev_min)
            _reset_cache_latch()
        except Exception:  # pragma: no cover
            pass

    return undo


def disable(fn: Callable) -> Callable:
    """Reference compiler.disable decorator — marks a function to stay out
    of graph capture. JAX equivalent: the function simply isn't jitted; for
    callers inside jit the right tool is jax.pure_callback, which this shim
    cannot insert automatically — so it returns the fn unchanged."""
    return fn


class CompiledModuleWrapper:

    def __init__(self, module, compile_config=None):
        self.module = module
        self._is_compiled = False

    def compile(self, *a, **kw):
        self._is_compiled = True
        return self.module

    @property
    def is_compiled(self) -> bool:
        return self._is_compiled


def attach_compile_api(engine) -> None:
    """Give an engine the reference's compile()/is_compiled surface
    (reference engine.py:3665: is_compiled is False until compile() runs)."""
    engine.is_compiled = False

    def compile(backend: Optional[str] = None, compile_kwargs: Optional[dict] = None,
                schedule: Any = None) -> None:
        logger.info("compile(): engine programs are XLA-compiled by construction; "
                    f"request recorded (backend={backend})")
        engine.is_compiled = True

    engine.compile = compile
