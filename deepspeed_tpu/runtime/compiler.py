"""torch.compile API shim.

Reference: ``runtime/compiler.py`` + ``engine.py:3665 compile()`` — opt-in
graph compilation of the wrapped module. Under this framework everything is
ALREADY traced and XLA-compiled at first dispatch (the engine jits
fwd_bwd/apply as whole programs), so ``compile()`` only records the request —
but ``is_compiled`` keeps the reference's contract: False until ``compile()``
has been called, True afterwards."""

import os
from typing import Any, Callable, Optional

from ..utils.logging import logger


def is_compile_supported() -> bool:
    return True


def _reset_cache_latch() -> None:
    """jax's compilation-cache module latches a "disabled" state at the
    first compile that runs with no cache dir configured (model.init, eager
    ops before engine construction all count). After that latch, config
    updates are silently ignored — entries log "cache is disabled/not
    initialized". reset_cache() clears the latch so the NEXT compile
    re-initializes against the directory just configured."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover — internals moved; cache is best-effort
        pass


# the path THIS function last applied (as opposed to the user/supervisor
# exporting JAX_COMPILATION_CACHE_DIR before launch): a later explicit
# ``compile.cache_dir`` may override a self-applied setting, but never a
# genuinely user-chosen cache — even one exported after a self-apply
_SELF_APPLIED_PATH = None


def default_cache_dir() -> str:
    """Default persistent-cache location, OUTSIDE any repo/working tree:
    ``$DS_TPU_COMPILE_CACHE_DIR`` if set, else
    ``$XDG_CACHE_HOME|~/.cache``/deepspeed_tpu/xla_cache. A cwd-relative
    default would litter project checkouts with compiled-program blobs (and
    tempt them into version control)."""
    env = os.environ.get("DS_TPU_COMPILE_CACHE_DIR")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "deepspeed_tpu", "xla_cache")


def configure_compile_cache(compile_config) -> Callable[[], None]:
    """Point JAX's persistent compilation cache at ``compile.cache_dir``
    (the autotuner's ``_enable_compile_cache`` promoted into engine init):
    multi-restart runs skip recompiles of the engine's step programs. An
    unset ``cache_dir`` falls back to :func:`default_cache_dir` (per-user,
    outside the repo tree).

    A pre-existing ``JAX_COMPILATION_CACHE_DIR`` env var or jax.config
    setting always wins — the engine never redirects a cache the user (or a
    supervisor process) already chose. (A cache this module itself applied
    earlier does not count as user-chosen: an explicit config may replace
    it.) The env var is also SET here so spawned child processes inherit the
    cache. Returns an undo() restoring prior state (no-op when nothing was
    applied).

    Also installs the process-wide XLA backend-compile listener
    (``ds_xla_backend_compile_seconds``): the compile-cache entry point is
    the one place every engine passes through before its first compile, so
    compiles that bypass the per-key ``CompileWatch`` wrappers (model init,
    eager ops, persistent-cache deserialization misses) are still visible.
    Idempotent; never blocks cache configuration."""
    global _SELF_APPLIED_PATH
    try:
        from ..observability.xla import install_backend_compile_listener
        install_backend_compile_listener()
    except Exception:  # pragma: no cover — telemetry must not break startup
        pass
    path = getattr(compile_config, "cache_dir", None)
    explicit = bool(path)
    if not path:
        path = default_cache_dir()
    import jax
    preset = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
              or getattr(jax.config, "jax_compilation_cache_dir", None))
    if preset and (preset != _SELF_APPLIED_PATH or not explicit):
        return lambda: None  # user's cache wins / default already in effect
    path = str(path)
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    prev_env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    prev_self = _SELF_APPLIED_PATH
    min_secs = getattr(compile_config, "cache_min_compile_secs", None)
    prev_min = getattr(jax.config,
                       "jax_persistent_cache_min_compile_time_secs", None)
    applied = False
    try:
        os.makedirs(path, exist_ok=True)
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
        jax.config.update("jax_compilation_cache_dir", path)
        if min_secs is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_secs))
        _reset_cache_latch()
        applied = True
        _SELF_APPLIED_PATH = path
    except Exception as e:  # pragma: no cover — the cache is an optimization
        logger.warning(f"persistent compile cache unavailable: {e}")

    def undo() -> None:
        global _SELF_APPLIED_PATH
        if not applied:
            return
        if prev_env is None:
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        else:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = prev_env
        _SELF_APPLIED_PATH = prev_self
        try:
            jax.config.update("jax_compilation_cache_dir", prev)
            if min_secs is not None:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", prev_min)
            _reset_cache_latch()
        except Exception:  # pragma: no cover
            pass

    return undo


def disable(fn: Callable) -> Callable:
    """Reference compiler.disable decorator — marks a function to stay out
    of graph capture. JAX equivalent: the function simply isn't jitted; for
    callers inside jit the right tool is jax.pure_callback, which this shim
    cannot insert automatically — so it returns the fn unchanged."""
    return fn


class CompiledModuleWrapper:

    def __init__(self, module, compile_config=None):
        self.module = module
        self._is_compiled = False

    def compile(self, *a, **kw):
        self._is_compiled = True
        return self.module

    @property
    def is_compiled(self) -> bool:
        return self._is_compiled


def attach_compile_api(engine) -> None:
    """Give an engine the reference's compile()/is_compiled surface
    (reference engine.py:3665: is_compiled is False until compile() runs)."""
    engine.is_compiled = False

    def compile(backend: Optional[str] = None, compile_kwargs: Optional[dict] = None,
                schedule: Any = None) -> None:
        logger.info("compile(): engine programs are XLA-compiled by construction; "
                    f"request recorded (backend={backend})")
        engine.is_compiled = True

    engine.compile = compile
