"""torch.compile API shim.

Reference: ``runtime/compiler.py`` + ``engine.py:3665 compile()`` — opt-in
graph compilation of the wrapped module. Under this framework everything is
ALREADY traced and XLA-compiled at first dispatch (the engine jits
fwd_bwd/apply as whole programs), so ``compile()`` only records the request —
but ``is_compiled`` keeps the reference's contract: False until ``compile()``
has been called, True afterwards."""

from typing import Any, Callable, Optional

from ..utils.logging import logger


def is_compile_supported() -> bool:
    return True


def disable(fn: Callable) -> Callable:
    """Reference compiler.disable decorator — marks a function to stay out
    of graph capture. JAX equivalent: the function simply isn't jitted; for
    callers inside jit the right tool is jax.pure_callback, which this shim
    cannot insert automatically — so it returns the fn unchanged."""
    return fn


class CompiledModuleWrapper:

    def __init__(self, module, compile_config=None):
        self.module = module
        self._is_compiled = False

    def compile(self, *a, **kw):
        self._is_compiled = True
        return self.module

    @property
    def is_compiled(self) -> bool:
        return self._is_compiled


def attach_compile_api(engine) -> None:
    """Give an engine the reference's compile()/is_compiled surface
    (reference engine.py:3665: is_compiled is False until compile() runs)."""
    engine.is_compiled = False

    def compile(backend: Optional[str] = None, compile_kwargs: Optional[dict] = None,
                schedule: Any = None) -> None:
        logger.info("compile(): engine programs are XLA-compiled by construction; "
                    f"request recorded (backend={backend})")
        engine.is_compiled = True

    engine.compile = compile
