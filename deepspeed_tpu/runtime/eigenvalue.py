"""Hessian max-eigenvalue estimation by power iteration (MoQ).

Reference: ``runtime/eigenvalue.py:13 Eigenvalue`` — per-block power
iteration on the loss Hessian, used by MoQ to schedule quantization
aggressiveness (flatter curvature → quantize earlier). The reference does
autograd-of-autograd with manual vector bookkeeping; JAX gives the
Hessian-vector product directly as ``jvp(grad(loss))`` — one fused XLA
program per iteration.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import logger


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn: Callable, params, seed: int = 0) -> float:
        """Largest |eigenvalue| of ∇²loss at params. loss_fn(params)→scalar."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params, ), (v, ))[1]

        key = jax.random.PRNGKey(seed)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(key, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])

        def norm(t):
            return jnp.sqrt(sum(jnp.vdot(x, x).real for x in jax.tree_util.tree_leaves(t)))

        eig = 0.0
        for i in range(self.max_iter):
            n = norm(v) + self.stability
            v = jax.tree_util.tree_map(lambda x: x / n, v)
            hv = hvp(v)
            new_eig = float(sum(jnp.vdot(a, b).real for a, b in zip(
                jax.tree_util.tree_leaves(v), jax.tree_util.tree_leaves(hv))))
            if abs(new_eig - eig) <= self.tol * max(abs(new_eig), 1e-12):
                eig = new_eig
                break
            eig, v = new_eig, hv
            if self.verbose:
                logger.info(f"eigenvalue iter {i}: {eig:.6f}")
        return abs(eig)
