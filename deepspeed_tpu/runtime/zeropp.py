"""ZeRO++ — quantized collectives (qwZ, qgZ) and hpZ wiring.

Rebuild of the reference's ZeRO++ paths (SURVEY.md §2.3):
- qwZ  (``zero_quantized_weights``  zero/config.py:287): the stage-3 weight
  allgather moves int8 blocks + fp32 scales instead of fp16 — half the
  allgather bytes (reference quantizes via ``csrc/quantization/
  swizzled_quantize.cu``; here via ``ops.quantizer`` Pallas/XLA kernels).
- qgZ  (``zero_quantized_gradients`` config.py:299 ->
  ``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``): the
  gradient reduce-scatter becomes quantize -> all-to-all -> local dequant+sum.
- hpZ  (``zero_hpz_partition_size`` config.py:283): secondary intra-node
  param shard so backward allgathers stay in the fast ICI domain — on TPU
  this is purely a mesh shape choice: split dp into (data, fsdp=hpz_size)
  with fsdp innermost (the ICI-contiguous axis); ``zero_axes_for`` then
  partitions over fsdp only. `hpz_mesh_axes` computes that split.

The wire format is a straight-through estimator: forward gathers
dequantize(all_gather(quantize(w))); backward reduce-scatters
dequant+sum(all_to_all(quantize(g))). XLA sees int8 collectives on the hot
path, autodiff sees the exact math.
"""

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.quantizer import quantize_int8_blockwise, dequantize_int8_blockwise

try:
    from jax import shard_map as _shard_map_new

    def _smap(f, mesh, in_specs, out_specs, manual):
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              axis_names=set(manual), check_vma=False)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _smap(f, mesh, in_specs, out_specs, manual):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                              check_rep=False)


def _axis_size(axis_name):
    return lax.psum(1, axis_name)


def _quant_blocks(flat, block):
    """Quantize a flat [n] vector with scales every `block` elems (n%block==0)."""
    rows = flat.reshape(-1, block).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(rows), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(rows / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def _dequant_blocks(values, scales, block):
    return (values.reshape(-1, block).astype(jnp.float32) *
            scales.reshape(-1, 1)).reshape(-1)


def _pick_block(n, block):
    b = min(block, n)
    while n % b != 0:
        b -= 1
    return max(b, 1)


def quantized_all_gather(x, axis_name: str, block: int = 2048):
    """qwZ wire op: int8-quantize the local shard, all-gather values+scales,
    dequantize. Per-shard view (inside shard_map); gathers dim 0."""
    shape = x.shape
    flat = x.reshape(-1)
    b = _pick_block(flat.shape[0], block)
    v, s = _quant_blocks(flat, b)
    v_all = lax.all_gather(v, axis_name, axis=0, tiled=True)
    s_all = lax.all_gather(s, axis_name, axis=0, tiled=True)
    full = _dequant_blocks(v_all, s_all, b)
    p = _axis_size(axis_name)
    return full.reshape((p * shape[0], ) + shape[1:]).astype(x.dtype)


def all_to_all_quant_reduce(g, axis_name: str, block: int = 2048):
    """qgZ wire op (reference ``coalesced_collectives.py:31``): reduce-scatter
    of `g` along dim 0 carried as int8: split into P chunks, quantize each,
    all-to-all, dequantize + sum. Per-shard view; returns this rank's chunk
    ([dim0/P, ...]) of the SUM over ranks."""
    p = _axis_size(axis_name)
    shape = g.shape
    assert shape[0] % p == 0, f"dim0 {shape[0]} not divisible by group size {p}"
    chunk = shape[0] // p
    n_local = chunk * int(np.prod(shape[1:])) if len(shape) > 1 else chunk
    flat = g.reshape(p, n_local)
    b = _pick_block(n_local, block)
    v, s = jax.vmap(lambda row: _quant_blocks(row, b))(flat)  # [p, n], [p, n/b]
    v_x = lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0, tiled=False)
    s_x = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=False)
    parts = jax.vmap(lambda vv, ss: _dequant_blocks(vv, ss, b))(v_x, s_x)
    return parts.sum(axis=0).reshape((chunk, ) + shape[1:]).astype(g.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def quantized_gather_param(x, axis_name: str, qgz: bool, block: int):
    """Straight-through qwZ gather with qgZ backward (see module docstring)."""
    return quantized_all_gather(x, axis_name, block)


def _qgp_fwd(x, axis_name, qgz, block):
    return quantized_all_gather(x, axis_name, block), None


def _qgp_bwd(axis_name, qgz, block, _, g):
    if qgz:
        return (all_to_all_quant_reduce(g, axis_name, block), )
    # exact reduce-scatter fallback
    return (lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True), )


quantized_gather_param.defvjp(_qgp_fwd, _qgp_bwd)


def hpz_mesh_axes(n_devices: int, hpz_partition_size: int) -> Dict[str, int]:
    """hpZ: dp split into (data=n/hpz, fsdp=hpz) so ZeRO partitions over the
    innermost (ICI-local) fsdp axis only — params replicate across nodes,
    shard within, exactly the reference's secondary partition."""
    if hpz_partition_size <= 1 or n_devices % hpz_partition_size != 0:
        return {"data": -1}
    return {"data": n_devices // hpz_partition_size, "fsdp": hpz_partition_size}


def make_qwz_param_gather(mesh_ctx, param_shardings, qgz: bool = False,
                          block: int = 2048,
                          zero_axes: tuple = ("data", "fsdp")):
    """Build `gather(params) -> full params` for use inside jit: every leaf
    sharded over the ZeRO axes is explicitly gathered through the int8 wire
    (fwd) and its gradient reduce-scattered through int8 (bwd, if qgz).

    Engine wiring for zero_quantized_weights: wraps the apply closure so XLA
    emits int8 collectives instead of implicit bf16 resharding.

    Only the dim sharded purely by ``zero_axes`` goes through the wire:
    under composed TP (``tensor_parallel``) a weight's model-axis dim is
    consumed sharded — there is no TP weight allgather to replace, and
    routing it through lossy int8 would change TP numerics. The shard_map
    is partial-manual over the ZeRO axes only, so a leaf's model-axis
    sharding rides through the wire gather untouched.
    """
    mesh = mesh_ctx.mesh

    def _leaf_gather(leaf, sharding):
        spec = sharding.spec if isinstance(sharding, NamedSharding) else P()
        # find the first dim sharded purely by ZeRO axes
        dim, axes = None, None
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            entry_t = entry if isinstance(entry, tuple) else (entry, )
            if all(a in zero_axes for a in entry_t):
                dim, axes = d, entry_t
                break
        if dim is None:
            return leaf
        axis_name = axes[0] if len(axes) == 1 else axes

        def per_shard(x):
            moved = jnp.moveaxis(x, dim, 0)
            full = quantized_gather_param(moved, axis_name, qgz, block)
            return jnp.moveaxis(full, 0, dim)

        # specs name ONLY the manual (ZeRO) axes: non-manual sharding (a TP
        # model axis on another dim) stays outside the manual region and is
        # preserved by the partial-manual shard_map
        in_spec = P(*(e if d == dim else None for d, e in enumerate(spec)))
        out_spec = P(*([None] * len(spec)))
        manual = set(axes)
        return _smap(per_shard, mesh, (in_spec, ), out_spec, manual)(leaf)

    def gather(params):
        return jax.tree_util.tree_map(_leaf_gather, params, param_shardings)

    return gather
