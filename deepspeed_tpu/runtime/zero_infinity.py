"""ZeRO-3 parameter offload (ZeRO-Infinity): layer-granular param streaming.

Reference: ``runtime/zero/stage3.py:614 _configure_tensor_swapping`` +
``runtime/swap_tensor/partitioned_param_swapper.py:37
AsyncPartitionedParameterSwapper`` + the ZeRO-3 hook machinery
(``runtime/zero/parameter_offload.py``): params live on host/NVMe between
uses; forward/backward hooks gather each submodule's params just-in-time and
release them after, so device memory holds only a sliding window of the model.

TPU-native shape of the idea (no module hooks, no streams):

- The model is an explicit LIST OF LAYERS (the same contract as
  ``pipe.module.PipelineModule`` — reference ``pipe/module.py:86``); each
  layer is a flax module or a ``fn(params, x) -> x`` callable.
- fp32 master params + Adam moments live on HOST DRAM (``device: cpu``) and
  never touch HBM. With ``device: nvme`` the compute (bf16) copies are
  persisted to NVMe through :class:`AsyncPartitionedParameterSwapper` and
  streamed back with async reads; moments can additionally ride the
  pipelined optimizer swapper via ``offload_optimizer: nvme``.
- Each step streams per-layer bf16 params host→device just-in-time with a
  ``prefetch`` window (``jax.device_put`` dispatches are async on TPU — the
  next layer's transfer flies while the current layer computes; this is the
  coordinator's ``__all_gather_params``/prefetch overlap,
  ``partitioned_param_coordinator.py:262``, without the trace machinery).
- Backward runs layer-by-layer via per-layer ``jax.vjp`` (which recomputes
  the layer forward — activation remat is inherent, matching the
  reference's recommended ZeRO-Infinity + activation-checkpointing combo),
  streaming gradients host-ward; numpy Adam steps layer k+1's grads while
  layer k's backward executes on device (Twin-Flow-style overlap).

Peak param HBM = (1 + prefetch) layers of compute-dtype params + one
layer's grads — INDEPENDENT of model depth. Layer-boundary activations are
O(depth) on device by default; enable
``activation_checkpointing.cpu_checkpointing`` to round-trip them through
host RAM and make total device residency depth-independent too. This is the
``max_live_parameters`` memory ceiling (reference ``zero/config.py:205-228``)
realized structurally instead of by a byte-counting governor.
"""

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist
from .host_offload import HostAdamOptimizer, flatten_tree, unflatten_like

try:
    import flax.linen as nn
    _HAS_FLAX = True
except ImportError:  # pragma: no cover
    _HAS_FLAX = False


def _as_layer_fn(layer):
    if _HAS_FLAX and isinstance(layer, nn.Module):
        def fn(params, x):
            return layer.apply({"params": params}, x)
        return fn
    if callable(layer):
        return layer
    raise TypeError(f"layer must be a flax Module or callable, got {type(layer)}")


def _bytes(tree) -> int:
    return int(sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree)))


class _LayerStreaming:
    """Just-in-time layer streaming shared by the training
    (:class:`ZeroInfinityEngine`) and inference (:class:`ZeroInferenceEngine`)
    executors: fetch-with-prefetch-window / release over a host or NVMe
    param store (reference ``partitioned_param_coordinator.py:262/:396``
    fetch_sub_module/release_sub_module).

    Subclass contract: ``_host_layer(i)`` returns layer i's compute-dtype
    host pytree; ``self._param_swapper`` is an
    ``AsyncPartitionedParameterSwapper`` or None (DRAM store);
    ``self._layer_keys[i]`` lists the store keys of layer i;
    ``self.n_layers`` is set. Call ``_stream_init()`` once in __init__.

    Residency semantics by store: with a DRAM store the prefetch window is
    ``jax.device_put`` dispatches, so device residency reaches
    ``(1 + prefetch)`` layers. With NVMe, the window stages HOST buffers
    only (async disk reads overlap compute; materializing device copies
    would block on each read), so device residency is ONE layer and the
    prefetch depth shows up as disk-read overlap, not HBM. Counters:
    ``bytes_streamed`` totals host→device transfers; ``peak_param_bytes``
    is the realized device ceiling under these semantics."""

    def _stream_init(self):
        self._dev_cache: Dict[int, object] = {}
        self._live_param_bytes = 0
        self.peak_param_bytes = 0   # observability: the realized HBM ceiling
        self.bytes_streamed = 0     # total host->device param traffic

    def _fetch(self, i: int):
        """Materialize layer i's params on device; kick the prefetch window.
        ≙ coordinator.fetch_sub_module (partitioned_param_coordinator.py:262)."""
        window = range(i + 1, min(i + 1 + self.prefetch, self.n_layers))
        return self._fetch_with_window(i, window)

    def _fetch_rev(self, i: int):
        """Backward-direction fetch: prefetch towards layer 0."""
        window = range(max(i - self.prefetch, 0), i)
        return self._fetch_with_window(i, window)

    def _fetch_with_window(self, i: int, window):
        if self._param_swapper is not None:
            # NVMe: issue async reads for the window; materializing their
            # device copies would block on each read, so only the current
            # layer goes to HBM here (the reads overlap this layer's compute)
            for j in window:
                if j not in self._dev_cache:
                    self._param_swapper.swap_in(self._layer_keys[j], async_op=True)
        else:
            for j in window:
                self._kick(j)
        self._kick(i)
        return self._dev_cache[i]

    def _kick(self, i: int):
        if i in self._dev_cache or i >= self.n_layers:
            return
        if self._param_swapper is not None:
            self._param_swapper.swap_in(self._layer_keys[i], async_op=True)
        p = jax.device_put(self._host_layer(i))  # async dispatch on TPU
        self._dev_cache[i] = p
        b = _bytes(p)
        self._live_param_bytes += b
        self.bytes_streamed += b
        self.peak_param_bytes = max(self.peak_param_bytes, self._live_param_bytes)

    def _release(self, i: int):
        """Drop layer i's device copy (≙ release_sub_module, coordinator:396)."""
        p = self._dev_cache.pop(i, None)
        if p is not None:
            self._live_param_bytes -= _bytes(p)
            for leaf in jax.tree_util.tree_leaves(p):
                leaf.delete()
        if self._param_swapper is not None:
            for k in self._layer_keys[i]:
                self._param_swapper.release(k)


class ZeroInfinityEngine(_LayerStreaming):
    """Training engine with ZeRO-3 parameter offload (``offload_param``).

    Exposes the engine step contract (``forward``/``backward``/``step``/
    ``train_batch``) over the streaming executor. Built by
    ``deepspeed_tpu.initialize`` when ``zero_optimization.offload_param.device``
    is ``cpu``/``nvme`` and the model is a layer list.
    """

    def __init__(self, layers: Sequence, layer_params: Sequence, loss_fn: Callable,
                 config):
        self._config = config
        zc = config.zero_config
        oc = zc.offload_param
        assert oc is not None and str(oc.device) != "none", \
            "ZeroInfinityEngine requires zero_optimization.offload_param"
        assert zc.stage >= 3, "parameter offload requires ZeRO stage 3"
        if config.fp16_enabled:
            # fp16 needs dynamic loss scaling + overflow-skip, which this
            # executor doesn't implement — refuse rather than diverge silently
            raise NotImplementedError(
                "offload_param training supports bf16/fp32; fp16 loss scaling "
                "is not implemented on the streaming executor (use bf16)")
        # compute copies follow the precision config (bf16 halves the
        # host->HBM stream bytes — the production setting; fp32 otherwise)
        self.compute_dtype = jnp.bfloat16 if config.bf16_enabled else jnp.float32
        self.prefetch = max(int(oc.buffer_count) - 1, 0)
        self._fns = [_as_layer_fn(l) for l in layers]
        self.loss_fn = loss_fn
        self.n_layers = len(self._fns)

        # host fp32 master, flat-keyed "layer{i}/<path>"
        host_master: Dict[str, np.ndarray] = {}
        self._layer_keys: List[List[str]] = []
        self._layer_like = []  # structure templates for unflatten
        for i, p in enumerate(layer_params):
            flat = {f"layer{i}/{k}": np.asarray(v, np.float32)
                    for k, v in flatten_tree(jax.tree_util.tree_map(np.asarray, p)).items()}
            host_master.update(flat)
            self._layer_keys.append(list(flat.keys()))
            self._layer_like.append(jax.tree_util.tree_map(lambda x: None, p))

        op = dict(config.optimizer_params or {})
        name = (config.optimizer_name or "adamw").lower()
        # lr schedule: same config surface as the main engine (engine.py)
        self._lr_scheduler = None
        lr_fn = None
        if config.scheduler_name is not None:
            from .lr_schedules import get_lr_schedule
            self._lr_scheduler = get_lr_schedule(config.scheduler_name,
                                                 config.scheduler_params or {},
                                                 base_lr=float(op.get("lr", 1e-3)))
            # HostAdam's t is 1-based at call time; lr_at is 0-based like the
            # device path's optax count
            lr_fn = lambda t: float(self._lr_scheduler.lr_at(t - 1))  # noqa: E731
        opt_swapper = None
        if zc.offload_optimizer_device == "nvme":
            from .swap_tensor import PipelinedOptimizerSwapper, AioConfig
            opt_swapper = PipelinedOptimizerSwapper(
                AioConfig(**(config._param_dict.get("aio", {}))),
                swap_folder=str(getattr(zc.offload_optimizer, "nvme_path", None)
                                or "/tmp/ds_tpu_offload"))
        # offload_param.device=nvme: the fp32 master itself lives on NVMe (the
        # swapper IS the master store — DRAM holds one leaf at a time); cpu:
        # master in DRAM, no NVMe traffic
        self._param_swapper = None
        if str(oc.device) == "nvme":
            from .swap_tensor import AsyncPartitionedParameterSwapper, AioConfig
            self._param_swapper = AsyncPartitionedParameterSwapper(
                AioConfig(**(config._param_dict.get("aio", {}))),
                swap_folder=str(oc.nvme_path or "/tmp/ds_tpu_param_swap"))
        self._total_elements = sum(v.size for v in host_master.values())
        self._host_optimizer = HostAdamOptimizer(
            host_master,
            lr=float(op.get("lr", 1e-3)),
            betas=tuple(op.get("betas", (0.9, 0.999))),
            eps=float(op.get("eps", 1e-8)),
            weight_decay=float(op.get("weight_decay", 0.0)),
            adamw_mode=(name == "adamw"),
            nvme_swapper=opt_swapper,
            lr_fn=lr_fn,
            master_swapper=self._param_swapper)
        del host_master  # NVMe mode: the swapper owns the bytes now

        # per-layer compiled programs (cached by layer index; identical-shape
        # layers share XLA's compile cache by jaxpr hash anyway)
        self._fwd_jit = [jax.jit(fn) for fn in self._fns]

        def _make_bwd(fn):
            def bwd(p, x, dy):
                _, vjp = jax.vjp(fn, p, x)
                return vjp(dy)
            return jax.jit(bwd)

        self._bwd_jit = [_make_bwd(fn) for fn in self._fns]
        self._loss_vag = jax.jit(jax.value_and_grad(
            lambda out, *rest: self.loss_fn(out, *rest)))

        # device-side streaming state (shared _LayerStreaming counters)
        self._stream_init()
        itemsize = jnp.dtype(self.compute_dtype).itemsize
        self.total_param_bytes = self._total_elements * itemsize

        # grad accumulation on HOST (stage-2-style: never resident on device
        # beyond one layer)
        self._host_grad_acc: Dict[str, np.ndarray] = {}
        self.micro_steps = 0
        self.global_steps = 0
        self.losses = None
        self._pending_loss = None
        log_dist(
            f"ZeroInfinityEngine: {self.n_layers} layers, "
            f"{self._total_elements/1e6:.1f}M params "
            f"offloaded to {oc.device}, prefetch={self.prefetch}", ranks=[0])

    # ------------------------------------------------------------------
    # param streaming
    # ------------------------------------------------------------------

    def _host_layer(self, i: int):
        """Layer i's compute-dtype copy as a host pytree."""
        dt = jnp.dtype(self.compute_dtype)  # numpy-compatible (ml_dtypes)
        flat = {}
        for k in self._layer_keys[i]:
            if self._param_swapper is not None:
                src = self._param_swapper.retrieve(k)
            else:
                src = self._host_optimizer.master[k]
            flat[k] = src.astype(dt)
        stripped = {k.split("/", 1)[1]: v for k, v in flat.items()}
        return unflatten_like(stripped, self._layer_like[i])

    # _fetch/_fetch_rev/_release come from _LayerStreaming.

    # ------------------------------------------------------------------
    # step
    # ------------------------------------------------------------------

    def forward(self, x, *loss_args):
        """Streamed forward + backward: computes the loss AND the host-side
        gradient accumulation in one pass (same forward-computes-grads
        contract as DeepSpeedTpuEngine — see its module docstring).

        Boundary activations are the remaining O(depth) device residency; with
        ``activation_checkpointing.cpu_checkpointing`` they round-trip through
        host RAM instead (reference ``checkpointing.py`` cpu_checkpointing),
        making device memory fully depth-independent.
        """
        if self._pending_loss is not None:
            raise RuntimeError(
                "forward() called twice without backward(); gradients are "
                "accumulated at forward time — a second forward would "
                "double-count (use a separate eval path for inference)")
        cpu_acts = self._config.activation_checkpointing_config.cpu_checkpointing
        acts = [np.asarray(x) if cpu_acts else x]
        h = x
        for i in range(self.n_layers):
            p = self._fetch(i)
            h = self._fwd_jit[i](p, h)
            acts.append(np.asarray(h) if cpu_acts and i < self.n_layers - 1 else h)
            if i < self.n_layers - 1:  # keep the last layer for backward start
                self._release(i)
        loss, dy = self._loss_vag(acts[-1], *loss_args)

        pending = []  # (layer, device grads) awaiting host accumulation
        for i in reversed(range(self.n_layers)):
            p = self._fetch_rev(i)
            a = jnp.asarray(acts[i]) if cpu_acts else acts[i]
            dp, dx = self._bwd_jit[i](p, a, dy)
            acts[i] = None  # consumed — free the device/host reference
            dy = dx
            self._release(i)
            for leaf in jax.tree_util.tree_leaves(dp):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            pending.append((i, dp))
            if len(pending) > 1:
                # host-accumulate the PREVIOUS layer while this one computes
                self._accumulate_host(*pending.pop(0))
        for item in pending:
            self._accumulate_host(*item)
        self._pending_loss = loss
        return loss

    def _accumulate_host(self, i: int, dp):
        flat = flatten_tree(jax.tree_util.tree_map(np.asarray, dp))
        for k, g in flat.items():
            key = f"layer{i}/{k}"
            if key in self._host_grad_acc:
                self._host_grad_acc[key] += np.asarray(g, np.float32)
            else:
                # np.asarray of a jax array is a read-only view — copy so
                # later micro-batches can accumulate in place
                self._host_grad_acc[key] = np.array(g, np.float32)

    def backward(self, loss, **kw):
        assert self._pending_loss is not None, "backward() without forward()"
        self._pending_loss = None
        self.losses = loss
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps % self.gradient_accumulation_steps()) == 0

    def step(self, lr_kwargs=None):
        if not (self.is_gradient_accumulation_boundary() and self.micro_steps > 0):
            return
        gas = self.gradient_accumulation_steps()
        grads = {k: g / gas for k, g in self._host_grad_acc.items()}
        clip = float(self._config.gradient_clipping or 0.0)
        if clip > 0:
            gnorm = float(np.sqrt(sum(float(np.sum(g.astype(np.float64)**2))
                                      for g in grads.values())))
            factor = min(1.0, clip / (gnorm + 1e-6))
            for g in grads.values():
                g *= factor
        # step_param writes NVMe-resident masters back through the swapper
        # itself; nothing extra to persist here
        self._host_optimizer.step(grads)
        self._host_grad_acc = {}
        self.global_steps += 1

    def train_batch(self, data_iter):
        losses = []
        for _ in range(self.gradient_accumulation_steps()):
            batch = next(data_iter)
            if not isinstance(batch, tuple):
                batch = (batch, )
            loss = self.forward(*batch)
            self.backward(loss)
            self.step()
            losses.append(float(loss))
        return sum(losses) / len(losses)

    # ------------------------------------------------------------------
    # info / checkpoint
    # ------------------------------------------------------------------

    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    @property
    def optimizer(self):
        return self

    @property
    def training_dataloader(self):
        return None

    @property
    def lr_scheduler(self):
        return self._lr_scheduler

    def get_lr(self):
        if self._lr_scheduler is not None:
            return [float(self._lr_scheduler.lr_at(max(self.global_steps - 1, 0)))]
        return [self._host_optimizer.lr]

    def save_checkpoint(self, save_dir, tag=None, client_state=None, **kw):
        import os
        import pickle
        tag = tag or f"global_step{self.global_steps}"
        if jax.process_index() == 0:  # host state is process-replicated
            path = os.path.join(save_dir, str(tag))
            os.makedirs(path, exist_ok=True)
            # leaf-streamed: one file per master/moment leaf, so checkpointing
            # never needs more DRAM than one leaf (the models this engine
            # exists for don't fit a whole-state pickle in host RAM)
            self._host_optimizer.save_state_files(os.path.join(path, "host_optimizer"))
            with open(os.path.join(path, "zero_infinity.pkl"), "wb") as f:
                pickle.dump({"global_steps": self.global_steps,
                             "micro_steps": self.micro_steps,
                             "client_state": client_state or {}}, f)
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(str(tag))
        return True

    def load_checkpoint(self, load_dir, tag=None, **kw):
        import os
        import pickle
        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        with open(os.path.join(path, "zero_infinity.pkl"), "rb") as f:
            sd = pickle.load(f)
        # re-seeds the NVMe master store through the master_swapper when
        # params live on disk; DRAM mode fills the master dict leaf by leaf
        opt_dir = os.path.join(path, "host_optimizer")
        if os.path.isdir(opt_dir):
            self._host_optimizer.load_state_files(opt_dir)
        elif "host_optimizer" in sd:  # earlier single-pickle layout
            self._host_optimizer.load_state_dict(sd["host_optimizer"])
        else:
            raise FileNotFoundError(f"no host optimizer state under {path}")
        self.global_steps = sd["global_steps"]
        self.micro_steps = sd["micro_steps"]
        return path, sd.get("client_state", {})


# ---------------------------------------------------------------------------
# ZeRO-Inference: forward-only weight streaming
# ---------------------------------------------------------------------------


class ZeroInferenceEngine(_LayerStreaming):
    """Forward-only ZeRO-Inference: model weights live on host DRAM or NVMe
    and stream to the device one layer at a time during decode.

    Reference: ZeRO-Inference (``deepspeed/inference`` with
    ``zero.offload_param``; ``blogs/deepspeed-gds/README.md:74`` — a 70B
    model decoding with weights streaming NVMe→HBM). Device residency is
    bounded by the layer window, independent of model depth — with a DRAM
    store ``(1 + prefetch)`` layers are device-resident; with NVMe the
    prefetch stages host read buffers and exactly ONE layer is
    device-resident (see :class:`_LayerStreaming`). Throughput at batch 1
    is NVMe/host-link bandwidth bound, which is the regime this engine
    exists for (big batch amortizes each streamed layer over more tokens).

    Contract mirrors :class:`ZeroInfinityEngine`'s layer list: ``layers[i]``
    is a flax module or ``fn(params, x) -> x``; embed/head stay caller-side
    (they are small and usually persistent). ``streamed_apply`` runs the
    whole stack over an activation; counters expose bytes streamed and the
    realized HBM ceiling so callers can journal achieved GB/s.
    """

    def __init__(self, layers: Sequence, layer_params: Sequence,
                 device: str = "cpu", nvme_path: Optional[str] = None,
                 prefetch: int = 1, dtype=jnp.bfloat16, aio_config=None):
        assert device in ("cpu", "nvme"), device
        self.compute_dtype = dtype
        self.prefetch = max(int(prefetch), 0)
        self._fns = [_as_layer_fn(l) for l in layers]
        self.n_layers = len(self._fns)
        self._fwd_jit = [jax.jit(fn) for fn in self._fns]

        dt = jnp.dtype(dtype)
        self._layer_keys: List[List[str]] = []
        self._layer_like = []
        self._host: Dict[str, np.ndarray] = {}
        self._param_swapper = None
        if device == "nvme":
            from .swap_tensor import AsyncPartitionedParameterSwapper, AioConfig
            self._param_swapper = AsyncPartitionedParameterSwapper(
                aio_config or AioConfig(),
                swap_folder=str(nvme_path or "/tmp/ds_tpu_zero_inference"))
        self.total_param_bytes = 0
        for i, p in enumerate(layer_params):
            # copy=False: params already at compute dtype pass through
            # without doubling host DRAM during init
            flat = {f"layer{i}/{k}": np.asarray(v).astype(dt, copy=False)
                    for k, v in flatten_tree(
                        jax.tree_util.tree_map(np.asarray, p)).items()}
            self._layer_keys.append(list(flat.keys()))
            self._layer_like.append(jax.tree_util.tree_map(lambda x: None, p))
            self.total_param_bytes += sum(v.nbytes for v in flat.values())
            if self._param_swapper is not None:
                for k, v in flat.items():
                    self._param_swapper.swap_out_and_release(k, v)  # weights PERSIST on NVMe
            else:
                self._host.update(flat)
        self._stream_init()
        log_dist(f"ZeroInferenceEngine: {self.n_layers} layers streaming "
                 f"from {device}, prefetch={self.prefetch}", ranks=[0])

    def _host_layer(self, i: int):
        flat = {}
        for k in self._layer_keys[i]:
            src = (self._param_swapper.retrieve(k)
                   if self._param_swapper is not None else self._host[k])
            flat[k] = src
        stripped = {k.split("/", 1)[1]: v for k, v in flat.items()}
        return unflatten_like(stripped, self._layer_like[i])

    def streamed_apply(self, x):
        """Run the full layer stack over ``x``, streaming weights
        just-in-time with the prefetch window (coordinator fetch/release,
        reference ``partitioned_param_coordinator.py:262/:396``; residency
        semantics per store: see :class:`_LayerStreaming`)."""
        for i in range(self.n_layers):
            p = self._fetch(i)
            x = self._fwd_jit[i](p, x)
            self._release(i)
        return x
