"""Anomaly sentry: windowed loss-spike / overflow / NaN detection.

The fp16 loss scaler already detects overflow per step (and skips the
update), but it cannot see two other production failure modes: NaN episodes
in full precision (no scaler in the loop — the poisoned update is applied),
and loss spikes from bad data that are numerically finite. The sentry
watches all three signals at step boundaries and, after
``max_consecutive_anomalies`` consecutive bad steps, tells the engine to
roll back to the last good checkpoint (``runtime/engine.py`` performs the
actual restore, keeping the data sampler's position so the offending window
is skipped rather than replayed).

Detection is host-side and cheap: in the async pipeline the already-fetched
window of losses is fed at drain time; in sync mode each step's loss is
observed directly. No extra device→host syncs are introduced.
"""

import math
from collections import deque
from typing import Optional

from ..utils.logging import logger


class AnomalySentry:
    """Consecutive-anomaly counter over three signals.

    ``observe(loss, overflow, step)`` returns the anomaly kind for this step
    (``"overflow"``, ``"nonfinite_loss"``, ``"loss_spike"``) or None; the
    engine checks ``should_rollback`` afterwards. A healthy step resets the
    consecutive counter and joins the spike-detector's reference window.
    """

    def __init__(self, max_consecutive: int = 3, spike_window: int = 20,
                 spike_factor: float = 3.0, spike_min_history: int = 5,
                 monitor=None):
        self.max_consecutive = max(1, int(max_consecutive))
        self.spike_factor = float(spike_factor)
        self.spike_min_history = max(1, int(spike_min_history))
        self._good = deque(maxlen=max(2, int(spike_window)))
        self.consecutive = 0
        self.total_anomalies = 0
        self.rollbacks = 0
        self._monitor = monitor

    # -- detection ---------------------------------------------------------

    def _spike_threshold(self) -> Optional[float]:
        if len(self._good) < self.spike_min_history:
            return None
        ordered = sorted(self._good)
        mid = len(ordered) // 2
        median = (ordered[mid] if len(ordered) % 2
                  else 0.5 * (ordered[mid - 1] + ordered[mid]))
        # abs() keeps the factor meaningful for near-zero / negative losses
        # (e.g. log-prob objectives); +1e-8 avoids a degenerate 0 threshold
        return abs(median) * self.spike_factor + 1e-8

    def observe(self, loss: Optional[float], overflow: bool,
                step: int) -> Optional[str]:
        kind = None
        if overflow:
            kind = "overflow"
        elif loss is not None and not math.isfinite(loss):
            kind = "nonfinite_loss"
        elif loss is not None:
            thr = self._spike_threshold()
            if thr is not None and abs(loss) > thr:
                kind = "loss_spike"
        if kind is None:
            if loss is not None and math.isfinite(loss):
                self._good.append(float(loss))
            self.consecutive = 0
            return None
        self.consecutive += 1
        self.total_anomalies += 1
        logger.warning(
            f"[sentry] step {step}: {kind} (loss={loss}), consecutive "
            f"{self.consecutive}/{self.max_consecutive}")
        if self._monitor is not None:
            self._monitor.write_events([
                ("Train/Sentry/anomaly", self.consecutive, step)])
        return kind

    @property
    def should_rollback(self) -> bool:
        return self.consecutive >= self.max_consecutive

    # -- rollback bookkeeping ---------------------------------------------

    def note_rollback(self, tag, step: int):
        self.rollbacks += 1
        self.consecutive = 0
        self._good.clear()  # post-rollback losses define a fresh baseline
        logger.warning(f"[sentry] step {step}: rolling back to checkpoint "
                       f"{tag!r} (rollback #{self.rollbacks})")
        if self._monitor is not None:
            self._monitor.write_events([
                ("Train/Sentry/rollback", self.rollbacks, step)])

    def reset(self):
        self.consecutive = 0
        self._good.clear()
