"""Data loaders (reference ``runtime/dataloader.py``: DeepSpeedDataLoader :41,
RepeatingLoader :17) — torch-free: datasets are sequences/iterables of numpy
or jax arrays; collation stacks to numpy (host) and the engine shards to
device via the batch sharding plan."""

import math
import time
from collections import deque
from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np

_DATA_WAIT_HIST = None


def _data_wait_hist():
    """Lazy module-level handle: host seconds spent blocked on the inner
    data iterator (the prefetch buffer's refill wait — nonzero means the
    input pipeline, not the device, is the bottleneck)."""
    global _DATA_WAIT_HIST
    if _DATA_WAIT_HIST is None:
        from ..observability import get_registry
        _DATA_WAIT_HIST = get_registry().histogram(
            "ds_data_wait_seconds",
            "Host wall seconds blocked on the training data iterator per "
            "prefetch refill", lo=1e-6, hi=1e3, buckets_per_decade=10)
    return _DATA_WAIT_HIST


class RepeatingLoader:

    def __init__(self, loader):
        """Wraps an iterator to restart on StopIteration (reference :17)."""
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def default_collate(items):
    """Stack a list of samples; supports tuples/dicts/arrays."""
    first = items[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate([it[i] for it in items]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([it[k] for it in items]) for k in first}
    return np.stack([np.asarray(it) for it in items])


class DevicePrefetchIterator:
    """Double-buffered device-side input prefetch (async_pipeline tentpole).

    ``put_fn`` dispatches one host batch to device (typically
    ``jax.device_put`` against the engine's batch sharding). XLA transfers
    are ASYNC — the put returns immediately with arrays whose copies stream
    in the background — so keeping ``depth`` batches in flight overlaps
    host→device input movement with the current step's compute: by the time
    the consumer needs batch i+1, its transfer raced the step running on
    batch i.

    Ordering is preserved exactly; exhaustion of the host iterator drains
    the buffer and then raises StopIteration (an epoch boundary under a
    per-epoch host loader — re-iterate the wrapping ``PrefetchingLoader``
    for the next epoch)."""

    def __init__(self, host_iter, put_fn: Callable, depth: int = 2):
        self._iter = iter(host_iter)
        self._put = put_fn
        self.depth = max(1, int(depth))
        self._buf = deque()
        self._fill()

    def _fill(self):
        while len(self._buf) < self.depth:
            t0 = time.perf_counter()
            try:
                batch = next(self._iter)
            except StopIteration:
                return
            _data_wait_hist().record(time.perf_counter() - t0)
            self._buf.append(self._put(batch))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._buf:
            raise StopIteration
        batch = self._buf.popleft()
        # top up BEFORE returning: the refill transfer dispatches while the
        # caller consumes `batch`
        self._fill()
        return batch


class PrefetchingLoader:
    """Re-iterable prefetch wrap of a loader: each ``__iter__`` starts a
    fresh :class:`DevicePrefetchIterator` over the inner loader's epoch.
    Forwards ``len``/``set_epoch`` so it drops into training loops written
    against ``DeepSpeedDataLoader``."""

    def __init__(self, loader, put_fn: Callable, depth: int = 2):
        self.loader = loader
        self.put_fn = put_fn
        self.depth = depth

    def __len__(self):
        return len(self.loader)

    def set_epoch(self, epoch: int):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    @property
    def dataset(self):
        return getattr(self.loader, "dataset", None)

    def __iter__(self):
        return DevicePrefetchIterator(iter(self.loader), self.put_fn,
                                      self.depth)


class DeepSpeedDataLoader:
    """Batched loader over a map-style dataset (reference :41). Distributed
    sampling note: under SPMD single-controller the *global* batch is formed
    on host and sharded by the engine, so there is no per-rank sampler — the
    loader yields micro-batches of the global micro batch size * dp."""

    def __init__(self,
                 dataset: Sequence,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = False,
                 seed: int = 0,
                 drop_last: bool = True,
                 sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # index sampler (DeepSpeedDataSampler): yields index arrays —
        # curriculum difficulty gating lives there, not here
        self.sampler = sampler

    def __len__(self):
        if self.sampler is not None:
            # the sampler counts GLOBAL batches but yields gas micro-batches
            # per global batch — len must match what __iter__ yields
            return len(self.sampler) * getattr(self.sampler, "gas", 1)
        n = len(self.dataset) / self.batch_size
        return math.floor(n) if self.drop_last else math.ceil(n)

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def __iter__(self):
        if self.sampler is not None:
            for sel in self.sampler:
                yield self.collate_fn([self.dataset[int(i)] for i in sel])
            return
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        nb = len(self)
        for b in range(nb):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in sel])
