"""Data loaders (reference ``runtime/dataloader.py``: DeepSpeedDataLoader :41,
RepeatingLoader :17) — torch-free: datasets are sequences/iterables of numpy
or jax arrays; collation stacks to numpy (host) and the engine shards to
device via the batch sharding plan."""

import math
from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np


class RepeatingLoader:

    def __init__(self, loader):
        """Wraps an iterator to restart on StopIteration (reference :17)."""
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def default_collate(items):
    """Stack a list of samples; supports tuples/dicts/arrays."""
    first = items[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate([it[i] for it in items]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([it[k] for it in items]) for k in first}
    return np.stack([np.asarray(it) for it in items])


class DeepSpeedDataLoader:
    """Batched loader over a map-style dataset (reference :41). Distributed
    sampling note: under SPMD single-controller the *global* batch is formed
    on host and sharded by the engine, so there is no per-rank sampler — the
    loader yields micro-batches of the global micro batch size * dp."""

    def __init__(self,
                 dataset: Sequence,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = False,
                 seed: int = 0,
                 drop_last: bool = True,
                 sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # index sampler (DeepSpeedDataSampler): yields index arrays —
        # curriculum difficulty gating lives there, not here
        self.sampler = sampler

    def __len__(self):
        if self.sampler is not None:
            # the sampler counts GLOBAL batches but yields gas micro-batches
            # per global batch — len must match what __iter__ yields
            return len(self.sampler) * getattr(self.sampler, "gas", 1)
        n = len(self.dataset) / self.batch_size
        return math.floor(n) if self.drop_last else math.ceil(n)

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def __iter__(self):
        if self.sampler is not None:
            for sel in self.sampler:
                yield self.collate_fn([self.dataset[int(i)] for i in sel])
            return
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        nb = len(self)
        for b in range(nb):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in sel])
