"""AIO/swap configuration (reference ``runtime/swap_tensor/aio_config.py`` +
``constants.py`` AIO block). Same JSON keys."""

from pydantic import Field

from ...config.config_utils import ConfigModel


class AioConfig(ConfigModel):
    block_size: int = 1048576
    queue_depth: int = 32
    thread_count: int = 4
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False  # accepted for config parity; no GDS analog on TPU


def get_aio_config(param_dict: dict) -> AioConfig:
    return AioConfig(**param_dict.get("aio", {}))
