"""NVMe swapping of (partitioned) parameters.

Reference: ``runtime/swap_tensor/partitioned_param_swapper.py:37
AsyncPartitionedParameterSwapper`` — each param's local partition lives on
NVMe between uses; swap-in ahead of compute, swap-out (release) after.

TPU shape of the idea: the engine's ZeRO-3 state is a sharded pytree; the
swapper stores each leaf's *host* copy in one file per leaf and streams it
back into a reusable aligned buffer, then ``jax.device_put`` (with the
leaf's NamedSharding) re-materializes it on HBM. Prefetch = submit reads
for the next leaves while the current ones compute (dispatch-ordering
replaces CUDA streams).
"""

import os
from typing import Dict, List, Optional

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import logger
from .aio_config import AioConfig

_DTYPE_TAG = {"float32": "f4", "bfloat16": "bf16", "float16": "f2"}


class AsyncPartitionedParameterSwapper:

    def __init__(self, aio_config: Optional[AioConfig] = None,
                 swap_folder: str = "/tmp/ds_tpu_nvme_swap",
                 swap_element_size: int = 4):
        cfg = aio_config or AioConfig()
        self.swap_folder = swap_folder
        os.makedirs(swap_folder, exist_ok=True)
        self.aio = AsyncIOHandle(block_size=cfg.block_size, queue_depth=cfg.queue_depth,
                                 thread_count=cfg.thread_count)
        self._meta: Dict[str, dict] = {}          # name -> {shape, dtype, path}
        self._pending_writes: Dict[str, int] = {}  # name -> request id
        self._pending_reads: Dict[str, tuple] = {}  # name -> (rid, buffer)
        self._available: Dict[str, np.ndarray] = {}  # completed reads

    def _path(self, name: str) -> str:
        from urllib.parse import quote
        # injective encoding — "a/b" and "a.b" must not share a swap file
        return os.path.join(self.swap_folder, f"{quote(name, safe='')}.swp")

    # ---- swap out (device -> NVMe) ----

    def swap_out_and_release(self, name: str, array) -> None:
        """Write the host copy async; the caller drops its device reference
        (reference: param.ds_tensor freed after write completes)."""
        host = np.ascontiguousarray(np.asarray(array))
        path = self._path(name)
        # the dtype OBJECT, not .str: extension dtypes (ml_dtypes bfloat16 —
        # the ZeRO-Inference compute copies) stringify to raw-void '|V2',
        # which round-trips to an un-JAX-able buffer
        self._meta[name] = {"shape": host.shape, "dtype": host.dtype, "path": path}
        self._pending_writes[name] = self.aio.submit_write(path, host)

    def synchronize_writes(self) -> None:
        for name, rid in self._pending_writes.items():
            self.aio.wait(rid)
        self._pending_writes.clear()

    # ---- swap in (NVMe -> host buffer [-> device by caller]) ----

    def swap_in(self, names: List[str], async_op: bool = False):
        """Kick reads for `names`. With async_op, returns immediately —
        prefetch path; retrieve() blocks on completion."""
        for name in names:
            if name in self._pending_reads or name in self._available:
                continue  # already inflight/ready
            if name in self._pending_writes:  # write-then-read hazard
                self.aio.wait(self._pending_writes.pop(name))
            meta = self._meta[name]
            buf = np.empty(meta["shape"], dtype=meta["dtype"])
            self._pending_reads[name] = (self.aio.submit_read(meta["path"], buf), buf)
        if not async_op:
            for name in names:
                self._finish_read(name)

    def _finish_read(self, name: str) -> None:
        if name in self._pending_reads:
            rid, buf = self._pending_reads.pop(name)
            self.aio.wait(rid)
            self._available[name] = buf

    def retrieve(self, name: str) -> np.ndarray:
        """Blocking fetch of a swapped-in host buffer."""
        self._finish_read(name)
        return self._available.pop(name)

    def release(self, name: str) -> None:
        """Drop swapped-in buffer without persisting (params are read-only
        on NVMe during forward/backward)."""
        self._available.pop(name, None)

    def remove(self, name: str) -> None:
        meta = self._meta.pop(name, None)
        if meta and os.path.exists(meta["path"]):
            os.remove(meta["path"])

    @property
    def swapped_names(self) -> List[str]:
        return list(self._meta.keys())

    def swappable_tensor(self, array) -> bool:
        """Reference swappable_tensor: only worth swapping above IO-block
        granularity."""
        return getattr(array, "nbytes", 0) >= self.aio.block_size
