"""Generic async tensor swapper.

Reference: ``runtime/swap_tensor/async_swapper.py:19 AsyncTensorSwapper`` —
fire-and-forget swap-out of host buffers through the AIO handle, with a
synchronization barrier. The reference cycles pinned CUDA buffers; here the
"pinned" pool is plain page-aligned numpy (TPU host memory is the staging
tier — device→host already happened via np.asarray / jax.device_get).
"""

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import logger
from .aio_config import AioConfig


class AsyncTensorSwapper:

    def __init__(self, aio_handle: Optional[AsyncIOHandle] = None,
                 aio_config: Optional[AioConfig] = None):
        cfg = aio_config or AioConfig()
        self.aio = aio_handle or AsyncIOHandle(block_size=cfg.block_size,
                                               queue_depth=cfg.queue_depth,
                                               thread_count=cfg.thread_count)
        self._pending_writes: List[int] = []
        self._pending_reads: Dict[str, Tuple[int, np.ndarray]] = {}
        self.swapped_bytes = 0

    def swap_out_tensors(self, path_tensor_pairs: List[Tuple[str, np.ndarray]]) -> None:
        """Async write; caller must keep arrays alive until synchronize (the
        handle holds a ref as well)."""
        for path, arr in path_tensor_pairs:
            arr = np.ascontiguousarray(arr)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._pending_writes.append(self.aio.submit_write(path, arr))
            self.swapped_bytes += arr.nbytes

    def swap_in_tensors(self, path_buffer_pairs: List[Tuple[str, np.ndarray]]) -> None:
        for path, buf in path_buffer_pairs:
            self._pending_reads[path] = (self.aio.submit_read(path, buf), buf)

    def synchronize_writes(self) -> None:
        for rid in self._pending_writes:
            self.aio.wait(rid)
        self._pending_writes.clear()

    def synchronize_reads(self) -> Dict[str, np.ndarray]:
        out = {}
        for path, (rid, buf) in self._pending_reads.items():
            self.aio.wait(rid)
            out[path] = buf
        self._pending_reads.clear()
        return out
