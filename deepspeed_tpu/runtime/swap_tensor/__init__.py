from .aio_config import AioConfig, get_aio_config
from .async_swapper import AsyncTensorSwapper
from .partitioned_param_swapper import AsyncPartitionedParameterSwapper
from .optimizer_swapper import OptimizerSwapper, PipelinedOptimizerSwapper
from .nvme_stream import NvmeToHbmStreamer
