"""NVMe → HBM streaming loader (the GDS analog).

Reference: ``csrc/gds/py_lib/deepspeed_py_gds_handle.cpp`` moves NVMe bytes
straight into GPU memory (9.6 GB/s read,
``blogs/deepspeed-gds/README.md:50``). TPUs have no GPUDirect analog — the
path is NVMe → pinned host buffer → HBM — so the bandwidth play is a
PIPELINE: the C++ AIO thread pool (``csrc/aio/ds_aio.cpp``) reads chunk
``i+1`` while ``jax.device_put`` streams chunk ``i``, with a ring of
reusable host buffers. Steady-state throughput ≈ min(NVMe read BW, PCIe
host→HBM BW) instead of their serial sum — the same double-buffering the
reference's bounce-buffer GDS fallback uses (``deepspeed_gds_op.cpp``).

``bin/ds_nvme_bench`` measures the achieved GB/s on real hardware (the
ZeRO-Inference bar: reference blog 6 tok/s bounce vs 7 tok/s GDS came from
exactly this path feeding weights).
"""

import time
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.aio import AsyncIOHandle, aligned_empty
from .aio_config import AioConfig

DEFAULT_CHUNK = 64 << 20  # 64 MiB: big enough to saturate, small enough to ring


class NvmeToHbmStreamer:
    """Pipelined file → device-array reader."""

    def __init__(self, aio_config: Optional[AioConfig] = None,
                 chunk_bytes: int = DEFAULT_CHUNK, num_buffers: int = 2,
                 use_o_direct: bool = False):
        cfg = aio_config or AioConfig()
        self.aio = AsyncIOHandle(block_size=cfg.block_size,
                                 queue_depth=cfg.queue_depth,
                                 thread_count=cfg.thread_count,
                                 use_o_direct=use_o_direct)
        self.chunk_bytes = int(chunk_bytes)
        # reusable host staging ring (≙ the reference's pinned bounce
        # buffers); 4096-aligned so O_DIRECT preads land straight in them
        self._ring = [aligned_empty(self.chunk_bytes)
                      for _ in range(max(2, num_buffers))]
        # XLA's CPU backend zero-copy-aliases numpy inputs — reusing the ring
        # would corrupt "device" chunks there; TPU device_put always copies
        # into HBM, so the ring is safe once the transfer completes
        self._put_copies = jax.default_backend() == "cpu"

    def read_to_device(self, path: str, nbytes: int, dtype, shape,
                       sharding=None) -> jax.Array:
        """Read `nbytes` from `path` into a device array of shape/dtype.

        Chunk i's host→HBM transfer (async XLA dispatch) overlaps chunk
        i+1's NVMe read (async AIO submit) — neither leg waits for the
        other's tail.
        """
        itemsize = jnp.dtype(dtype).itemsize
        if self.chunk_bytes % itemsize or nbytes % itemsize:
            raise ValueError(f"chunk_bytes={self.chunk_bytes} and nbytes={nbytes} "
                             f"must be multiples of {dtype} itemsize {itemsize}")
        n_chunks = max(1, (nbytes + self.chunk_bytes - 1) // self.chunk_bytes)

        if self._put_copies:
            # CPU backend: XLA's concatenate collapses past ~2 GB (measured
            # 0.17 GB/s at 32 chunks) and device_put is a memcpy anyway — so
            # fan ALL chunk reads out to the AIO pool into one host buffer,
            # then hand XLA a single contiguous array. The overlapped
            # per-chunk path below is the TPU shape (PCIe transfer of chunk i
            # rides alongside the NVMe read of chunk i+1; HBM concat is
            # effectively free).
            # fresh per-call buffer: XLA zero-copy-aliases numpy inputs on
            # this backend, so the buffer handed to device_put must never be
            # reused — ownership transfers to the returned array (the view's
            # .base keeps the aligned backing alive). Striped pread: one
            # Request is served serially by one worker, so the fan-out is
            # what actually engages the thread pool on this bulk load.
            buf = aligned_empty(nbytes)
            got = self.aio.pread_striped(path, buf)
            if got != nbytes:
                raise IOError(f"short read from {path}: wanted {nbytes}, got {got}")
            arr = jax.device_put(buf.view(np.dtype(dtype)).reshape(shape))
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            return arr

        device_chunks = []
        pending: Tuple[int, int, int] = None  # (req_id, ring_slot, size)
        in_flight = [None] * len(self._ring)  # device chunk using each slot

        def submit(i):
            off = i * self.chunk_bytes
            size = min(self.chunk_bytes, nbytes - off)
            slot = i % len(self._ring)
            if in_flight[slot] is not None:
                # the device must be done pulling from this slot before the
                # AIO pool overwrites it (no extra host copy that way)
                in_flight[slot].block_until_ready()
                in_flight[slot] = None
            rid = self.aio.submit_read(path, self._ring[slot][:size], offset=off)
            return (rid, slot, size)

        pending = submit(0)
        for i in range(n_chunks):
            rid, slot, size = pending
            got = self.aio.wait(rid)
            if got != size:
                raise IOError(f"short read from {path}: chunk {i} wanted {size} "
                              f"bytes, got {got} — a silently-truncated tensor "
                              f"would be garbage")
            # dtype reinterpretation happens on the HOST view (free) — a
            # device-side bitcast would be a whole extra memory pass
            src = self._ring[slot][:size].view(np.dtype(dtype))
            dev = jax.device_put(src.copy() if self._put_copies else src)
            in_flight[slot] = None if self._put_copies else dev
            device_chunks.append(dev)
            if i + 1 < n_chunks:
                pending = submit(i + 1)  # next read flies during the transfer
        flat = device_chunks[0] if len(device_chunks) == 1 else jnp.concatenate(device_chunks)
        arr = flat.reshape(shape)
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        return arr

    def read_to_sharded(self, path: str, dtype, shape, sharding) -> jax.Array:
        """Read a ROW-SHARDED (dim-0) tensor straight into its shards: each
        device's slice streams from its own byte range and lands on its
        device — the full array never materializes on one device (the
        ZeRO-Inference weight-feeding case; the plain path would OOM on
        tensors bigger than a single chip's HBM). Falls back to
        read_to_device for other sharding layouts."""
        itemsize = jnp.dtype(dtype).itemsize
        row_bytes = int(np.prod(shape[1:])) * itemsize if len(shape) > 1 else itemsize
        idx_map = sharding.addressable_devices_indices_map(tuple(shape))

        def _row_contiguous(idx):
            if len(idx) != len(shape):
                return False
            for ax, s in enumerate(idx[1:], start=1):
                if (s.start or 0) != 0 or (s.stop or shape[ax]) != shape[ax]:
                    return False
            return True

        if not all(_row_contiguous(ix) for ix in idx_map.values()):
            nbytes = int(np.prod(shape)) * itemsize
            return self.read_to_device(path, nbytes, dtype, shape, sharding)

        shards = []
        range_cache = {}  # (start, stop) -> host buffer: replicated rows read ONCE
        for dev, idx in idx_map.items():
            s0 = idx[0]
            start, stop = s0.start or 0, s0.stop or shape[0]
            host = range_cache.get((start, stop))
            if host is None:
                n = (stop - start) * row_bytes
                host = aligned_empty(n)
                # pipelined: chunk i+1's read flies while chunk i memcpys out
                # of the AIO ring into the shard buffer
                n_chunks = max(1, (n + self.chunk_bytes - 1) // self.chunk_bytes)

                def sub(i):
                    off = i * self.chunk_bytes
                    size = min(self.chunk_bytes, n - off)
                    slot = i % len(self._ring)
                    rid = self.aio.submit_read(path, self._ring[slot][:size],
                                               offset=start * row_bytes + off)
                    return rid, slot, size, off

                pend = sub(0)
                for i in range(n_chunks):
                    rid, slot, size, off = pend
                    got = self.aio.wait(rid)
                    if got != size:
                        raise IOError(f"short read from {path} at offset {off}")
                    if i + 1 < n_chunks:
                        nxt = sub(i + 1)
                    host[off:off + size] = self._ring[slot][:size]
                    if i + 1 < n_chunks:
                        pend = nxt
                range_cache[(start, stop)] = host
            shard_shape = (stop - start, *shape[1:])
            shards.append(jax.device_put(
                host.view(jnp.dtype(dtype)).reshape(shard_shape), dev))
        return jax.make_array_from_single_device_arrays(tuple(shape), sharding, shards)

    def benchmark(self, path: str, nbytes: int, iters: int = 3) -> dict:
        """Measure pipelined NVMe→HBM GB/s for an existing file; compare
        against the serial (read-everything-then-put) baseline."""
        # pipelined
        t0 = time.perf_counter()
        for _ in range(iters):
            arr = self.read_to_device(path, nbytes, jnp.uint8, (nbytes, ))
            jax.block_until_ready(arr)
        piped = nbytes * iters / (time.perf_counter() - t0)
        # serial baseline — aligned destination so O_DIRECT preads land
        # straight in it (unaligned would bounce+memcpy and understate the
        # baseline; the comparison must be against serial's best case)
        buf = aligned_empty(nbytes)
        t0 = time.perf_counter()
        for _ in range(iters):
            self.aio.pread(path, buf)
            arr = jax.device_put(buf)
            jax.block_until_ready(arr)
        serial = nbytes * iters / (time.perf_counter() - t0)
        return {"pipelined_gbps": piped / 1e9, "serial_gbps": serial / 1e9,
                "speedup": piped / max(serial, 1e-9)}

    def close(self):
        self.aio.close()
