"""Optimizer-state NVMe swapping.

Reference: ``runtime/swap_tensor/partitioned_optimizer_swapper.py`` (swap
state in around each sub-group's optimizer step) and
``pipelined_optimizer_swapper.py`` (overlap next sub-group's read + previous
sub-group's write with the current step — double buffering). The TPU engine
steps sub-groups of the optimizer pytree; these classes provide the same
swap-in → step → swap-out choreography over host numpy state.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

from ...utils.logging import logger
from .aio_config import AioConfig
from .partitioned_param_swapper import AsyncPartitionedParameterSwapper


class OptimizerSwapper:
    """Blocking variant (reference partitioned_optimizer_swapper.py)."""

    def __init__(self, aio_config: Optional[AioConfig] = None,
                 swap_folder: str = "/tmp/ds_tpu_nvme_swap_optim"):
        self._swapper = AsyncPartitionedParameterSwapper(aio_config, swap_folder)

    def swap_out_optimizer_state(self, group_name: str, state: Dict[str, np.ndarray]) -> None:
        for key, arr in state.items():
            self._swapper.swap_out_and_release(f"{group_name}.{key}", arr)
        self._swapper.synchronize_writes()

    def swap_in_optimizer_state(self, group_name: str, keys: List[str]) -> Dict[str, np.ndarray]:
        names = [f"{group_name}.{k}" for k in keys]
        self._swapper.swap_in(names)
        return {k: self._swapper.retrieve(n) for k, n in zip(keys, names)}

    def purge(self, group_name: str, keys: List[str]) -> None:
        for k in keys:
            self._swapper.remove(f"{group_name}.{k}")


class PipelinedOptimizerSwapper(OptimizerSwapper):
    """Overlapped variant (reference pipelined_optimizer_swapper.py:
    OVERLAP_SWAP_IN/OUT): prefetch group i+1 while stepping group i; writes
    drain in the background and only synchronize at the end."""

    def step_groups(self, group_names: List[str], keys: List[str],
                    step_fn: Callable[[str, Dict[str, np.ndarray]], Dict[str, np.ndarray]]):
        """Run `step_fn(group, state)->new_state` over every group with
        IO/compute overlap."""
        if not group_names:
            return
        names = lambda g: [f"{g}.{k}" for k in keys]
        self._swapper.swap_in(names(group_names[0]), async_op=True)
        for i, group in enumerate(group_names):
            if i + 1 < len(group_names):  # prefetch next while current steps
                self._swapper.swap_in(names(group_names[i + 1]), async_op=True)
            state = {k: self._swapper.retrieve(n) for k, n in zip(keys, names(group))}
            new_state = step_fn(group, state)
            for key, arr in new_state.items():
                self._swapper.swap_out_and_release(f"{group}.{key}", arr)
        self._swapper.synchronize_writes()
