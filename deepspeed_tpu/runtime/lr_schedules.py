"""LR schedules.

Rebuild of reference ``deepspeed/runtime/lr_schedules.py`` (LRRangeTest :267,
OneCycle :370, WarmupLR :634, WarmupDecayLR :723, WarmupCosineLR :774) with the
same schedule names and JSON param keys. Each schedule is a host-side object
with the reference's ``step()/get_lr()/state_dict()`` API **and** a pure
``lr_at(step)`` usable inside a jitted train step (all math is jnp-safe).
"""

import math
from typing import Optional

from ..utils.logging import logger

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


class _LRScheduleBase:
    """Host-side schedule with reference API; subclasses define _lr(step)."""

    def __init__(self, optimizer=None, last_batch_iteration: int = -1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def _lr(self, step: int) -> float:
        raise NotImplementedError

    def lr_at(self, step):
        """Pure function of step (jnp-friendly) for use inside jit."""
        return self._lr(step)

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler before it has started")
            return [0.0]
        return [self._lr(self.last_batch_iteration)]

    def get_last_lr(self):
        if getattr(self, "_last_lr", None) is None:
            # before the first step(): the schedule's value at the current
            # iteration (reference asserts here; returning the real value is
            # strictly more useful and keeps engine.get_lr() exception-free)
            return [self._lr(max(self.last_batch_iteration, 0))]
        return self._last_lr

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = [self._lr(self.last_batch_iteration)]
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self._last_lr[0])

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_LRScheduleBase):
    """LR range test (reference :267): linearly/staircase-increasing LR."""

    def __init__(self,
                 optimizer=None,
                 lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        if lr_range_test_min_lr <= 0:
            raise ValueError(f"LR range test minimum lr={lr_range_test_min_lr}, must be > 0")
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def _lr(self, step):
        import jax.numpy as jnp
        count = step / self.step_size
        if self.staircase:
            count = jnp.floor(count) if not isinstance(count, float) else math.floor(count)
        return self.min_lr * (1 + count * self.step_rate)


class OneCycle(_LRScheduleBase):
    """1-cycle policy (reference :370): up phase, down phase, then decay."""

    def __init__(self,
                 optimizer=None,
                 cycle_min_lr: float = 1e-4,
                 cycle_max_lr: float = 1e-3,
                 decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0,
                 cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.8,
                 cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0,
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_step_size = cycle_first_step_size
        self.second_step_size = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_cycle_size = self.first_step_size + self.second_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def _lr(self, step):
        import jax.numpy as jnp
        step = jnp.asarray(step, dtype=jnp.float32)
        in_up = step < self.first_step_size
        in_cycle = step < self.total_cycle_size
        up_frac = jnp.clip(step / max(self.first_step_size, 1), 0.0, 1.0)
        down_frac = jnp.clip((step - self.first_step_size) / max(self.second_step_size, 1), 0.0, 1.0)
        lr_up = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * up_frac
        lr_down = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * down_frac
        # decay phase after the cycle
        decay_steps = jnp.maximum(step - self.total_cycle_size, 0.0)
        if self.decay_step_size > 0:
            decay_count = jnp.floor(decay_steps / self.decay_step_size)
        else:
            decay_count = decay_steps
        lr_decay = self.cycle_min_lr / (1.0 + decay_count * self.decay_lr_rate)
        return jnp.where(in_up, lr_up, jnp.where(in_cycle, lr_down, lr_decay))

    def get_mom(self):
        if not self.cycle_momentum:
            return None
        step = max(self.last_batch_iteration, 0)
        if step < self.first_step_size:
            frac = step / max(self.first_step_size, 1)
            return self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * frac
        elif step < self.total_cycle_size:
            frac = (step - self.first_step_size) / max(self.second_step_size, 1)
            return self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * frac
        return self.cycle_max_mom


class WarmupLR(_LRScheduleBase):
    """Warmup then hold (reference :634). warmup_type: log|linear."""

    def __init__(self,
                 optimizer=None,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 warmup_type: str = "log",
                 last_batch_iteration: int = -1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        if warmup_type not in ("log", "linear"):
            logger.warning(f"Using unknown warmup_type: {warmup_type}. The increasing function "
                           "is set to default (log)")
            warmup_type = "log"
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _gamma(self, step):
        import jax.numpy as jnp
        step = jnp.asarray(step, dtype=jnp.float32)
        if self.warmup_type == "log":
            g = self.inverse_log_warm_up * jnp.log(jnp.maximum(step, 1.0))
        else:
            g = step / self.warmup_num_steps
        return jnp.clip(g, 0.0, 1.0)

    def _lr(self, step):
        g = self._gamma(step)
        return self.min_lr + (self.max_lr - self.min_lr) * g


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero over total_num_steps (reference :723)."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps: int = 10000,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 warmup_type: str = "log",
                 last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type,
                         last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning("total_num_steps {} is less than warmup_num_steps {}".format(
                total_num_steps, warmup_num_steps))

    def _gamma(self, step):
        import jax.numpy as jnp
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = super()._gamma(step)
        decay = jnp.maximum(
            0.0, (self.total_num_steps - step) /
            max(self.total_num_steps - self.warmup_num_steps, 1))
        return jnp.where(step < self.warmup_num_steps, warm, decay)


class WarmupCosineLR(_LRScheduleBase):
    """Warmup then cosine decay (reference :774); ratios of the optimizer lr."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps: int = 10000,
                 warmup_min_ratio: float = 0.0,
                 warmup_num_steps: int = 1000,
                 cos_min_ratio: float = 0.0001,
                 last_batch_iteration: int = -1,
                 base_lr: float = 1.0):
        super().__init__(optimizer, last_batch_iteration)
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio
        self.base_lr = base_lr
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning("total_num_steps {} is less than warmup_num_steps {}".format(
                total_num_steps, warmup_num_steps))

    def _lr(self, step):
        import jax.numpy as jnp
        step = jnp.asarray(step, dtype=jnp.float32)
        warm_ratio = self.warmup_min_ratio + (1.0 - self.warmup_min_ratio) * jnp.clip(
            step / self.warmup_num_steps, 0.0, 1.0)
        frac = jnp.clip((step - self.warmup_num_steps) /
                        max(self.total_num_steps - self.warmup_num_steps, 1), 0.0, 1.0)
        cos_ratio = self.cos_min_ratio + (1.0 - self.cos_min_ratio) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac))
        ratio = jnp.where(step < self.warmup_num_steps, warm_ratio, cos_ratio)
        return self.base_lr * ratio


SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def get_lr_schedule(name: str, params: dict, optimizer=None, base_lr: Optional[float] = None):
    if name not in SCHEDULE_CLASSES:
        raise ValueError(f"Unknown LR schedule {name}; valid: {VALID_LR_SCHEDULES}")
    kwargs = dict(params)
    if name == WARMUP_COSINE_LR and base_lr is not None:
        kwargs.setdefault("base_lr", base_lr)
    return SCHEDULE_CLASSES[name](optimizer=optimizer, **kwargs)
