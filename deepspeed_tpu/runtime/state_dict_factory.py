"""MP checkpoint merge/split at load time.

Reference: ``runtime/state_dict_factory.py`` (SDLoaderFactory /
MegatronSDLoader): load a checkpoint saved at TP degree N into a job running
TP degree M by merging or splitting the parallel dimension of each
column/row-parallel weight.

TPU note: checkpoints written by THIS framework never need it — orbax stores
full logical arrays. This exists for *imported* shard sets (Megatron-style
per-rank files converted to numpy trees).
"""

from typing import Dict, List, Sequence

import numpy as np

from ..utils.logging import logger


def merge_parallel_dim(shards: Sequence[np.ndarray], axis: int) -> np.ndarray:
    """Concatenate per-rank shards back to the full weight (ckpt_mp > run_mp
    path of reference merge_state_dict)."""
    return np.concatenate(list(shards), axis=axis)


def split_parallel_dim(full: np.ndarray, num_shards: int, axis: int) -> List[np.ndarray]:
    """Split a full weight for a larger TP degree (reference split_state_dict)."""
    if full.shape[axis] % num_shards:
        raise ValueError(f"dim {axis} of {full.shape} not divisible by {num_shards}")
    return list(np.split(full, num_shards, axis=axis))


class SDLoaderFactory:

    @staticmethod
    def get_sd_loader_json(json_or_dict, checkpoint_engine=None):
        raise NotImplementedError("provide shard trees to SDLoader.merge/split directly")

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", checkpoint_engine=None, version=None):
        return SDLoader(ckpt_list)


class SDLoader:
    """Merge/split a list of per-TP-rank param trees (flat dicts
    {name: array}) onto a target TP degree, with reference semantics:
    column-parallel weights concatenate on the output dim, row-parallel on
    the input dim, embeddings on the vocab dim."""

    def __init__(self, shard_dicts: Sequence[Dict[str, np.ndarray]]):
        self.shards = list(shard_dicts)

    @staticmethod
    def _axis_for(name: str, ndim: int) -> int:
        from ..parallel.tp import _COL_PARALLEL, _ROW_PARALLEL
        if ndim < 2:
            return -1  # biases/norm scales replicate (matches tp.heuristic_spec)
        if _COL_PARALLEL.search(name):
            return ndim - 1  # flax kernels [in, out]: output dim
        if _ROW_PARALLEL.search(name):
            return max(0, ndim - 2)  # input dim
        if "embed" in name or "vocab" in name:
            return 0
        return -1  # replicated

    def merge(self) -> Dict[str, np.ndarray]:
        if len(self.shards) == 1:
            return dict(self.shards[0])
        out = {}
        for name, w0 in self.shards[0].items():
            axis = self._axis_for(name, w0.ndim)
            parts = [sd[name] for sd in self.shards]
            if axis < 0:
                out[name] = w0  # replicated: any rank's copy
            else:
                out[name] = merge_parallel_dim(parts, axis)
        return out

    def split(self, num_shards: int) -> List[Dict[str, np.ndarray]]:
        assert len(self.shards) == 1, "split() expects one merged tree"
        full = self.shards[0]
        outs = [dict() for _ in range(num_shards)]
        for name, w in full.items():
            axis = self._axis_for(name, w.ndim)
            if axis < 0:
                for o in outs:
                    o[name] = w
            else:
                for o, part in zip(outs, split_parallel_dim(w, num_shards, axis)):
                    o[name] = part
        return outs
