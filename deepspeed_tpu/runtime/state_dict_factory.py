"""MP checkpoint merge/split at load time.

Reference: ``runtime/state_dict_factory.py`` (SDLoaderFactory /
MegatronSDLoader): load a checkpoint saved at TP degree N into a job running
TP degree M by merging or splitting the parallel dimension of each
column/row-parallel weight; ``SDLoaderBase.load`` (reference
``state_dict_factory.py:56``) dispatches on run-vs-checkpoint degree, and
fused ``query_key_value`` weights get the version-dependent segment
reordering of reference ``merge_query_key_value`` (``:220``) /
``split_query_key_value`` (``:258``).

TPU note: checkpoints written by THIS framework never need it — orbax stores
full logical arrays. This exists for *imported* shard sets: Megatron-style
per-rank files (torch ``.pt``/``.bin``, numpy ``.npz``, flax ``.msgpack``)
or already-loaded numpy trees. Torch Linear weights are ``[out, in]`` while
flax kernels are ``[in, out]`` — the parallel axis follows the detected (or
declared) ``weight_layout``.
"""

import json
import os
import re
from typing import Dict, List, Sequence, Union

import numpy as np

from ..utils.logging import logger

# fused attention projections whose per-rank segments must be reordered on
# merge (reference merge_query_key_value): Megatron 'query_key_value',
# baichuan 'W_pack', phi-style 'qkv_proj'
_QKV = re.compile(r"(query_key_value|W_pack|qkv_proj|qkv\b)")


def merge_parallel_dim(shards: Sequence[np.ndarray], axis: int) -> np.ndarray:
    """Concatenate per-rank shards back to the full weight (ckpt_mp > run_mp
    path of reference merge_state_dict)."""
    return np.concatenate(list(shards), axis=axis)


def split_parallel_dim(full: np.ndarray, num_shards: int, axis: int) -> List[np.ndarray]:
    """Split a full weight for a larger TP degree (reference split_state_dict)."""
    if full.shape[axis] % num_shards:
        raise ValueError(f"dim {axis} of {full.shape} not divisible by {num_shards}")
    return list(np.split(full, num_shards, axis=axis))


def _to_numpy(v):
    """torch tensor / jax array / numpy → numpy (host)."""
    if isinstance(v, np.ndarray):
        return v
    if hasattr(v, "detach"):  # torch tensor
        t = v.detach().cpu()
        if t.dtype is not None and str(t.dtype) == "torch.bfloat16":
            t = t.float()
        return t.numpy()
    return np.asarray(v)


def load_state_file(path: str) -> Dict[str, np.ndarray]:
    """Load one on-disk shard into a flat {name: np.ndarray} dict.

    Formats: ``.npz`` (numpy archive), ``.msgpack`` (flax serialization),
    anything else is handed to ``torch.load`` (the reference's format —
    Megatron/DeepSpeed rank files; nested 'module'/'model' wrappers are
    unwrapped the way reference SDLoaderBase does)."""
    from .host_offload import flatten_tree
    if path.endswith(".npz"):
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    if path.endswith(".msgpack"):
        from flax.serialization import msgpack_restore
        with open(path, "rb") as f:
            return flatten_tree(msgpack_restore(f.read()))
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=False)
    for wrapper in ("module", "model", "state_dict"):
        if isinstance(sd, dict) and wrapper in sd and isinstance(sd[wrapper], dict):
            sd = sd[wrapper]
    return {k: _to_numpy(v) for k, v in flatten_tree(sd).items()
            if hasattr(v, "shape") or np.isscalar(v)}


class SDLoaderFactory:

    @staticmethod
    def get_sd_loader_json(json_or_dict, checkpoint_engine=None):
        """Reference ``state_dict_factory.py:23``: a checkpoint descriptor —
        ``{"type": ..., "checkpoints": [paths...], "version": ...}`` or a
        path to such a json — becomes a loader over its shard files."""
        data = json_or_dict
        if isinstance(data, str):
            base = os.path.dirname(os.path.abspath(data))
            with open(data) as f:
                data = json.load(f)
        else:
            base = ""
        sd_type = data.get("type", "Megatron")
        ckpts = data.get("checkpoints", [])
        if isinstance(ckpts, dict):  # bloom-style {tp_degree: [files]}
            raise NotImplementedError(
                "per-degree checkpoint maps are a BLOOM packaging detail; "
                "pass the file list for the saved degree directly")
        paths = [p if os.path.isabs(p) else os.path.join(base, p) for p in ckpts]
        return SDLoaderFactory.get_sd_loader(paths, sd_type=sd_type,
                                             version=data.get("version"))

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", checkpoint_engine=None,
                      version=None, weight_layout="auto"):
        return SDLoader(ckpt_list, version=version, weight_layout=weight_layout)


class SDLoader:
    """Merge/split a list of per-TP-rank param trees onto a target TP degree,
    with reference semantics: column-parallel weights concatenate on the
    output dim, row-parallel on the input dim, embeddings on the vocab dim,
    and fused qkv weights get per-rank segment reordering (ckpt version 0).

    Entries may be flat dicts {name: array} (in-memory) or file paths
    (loaded lazily per ``load`` call — a rank only reads the files its
    target shard needs, reference ``state_dict_factory.py:56``).

    ``weight_layout``: "flax" ([in, out] kernels), "torch" ([out, in]
    Linear weights), or "auto" — detected from the parameter names
    ("...kernel" → flax, "...weight" → torch, the two ecosystems' fixed
    spellings)."""

    def __init__(self, shard_dicts_or_paths: Sequence[Union[Dict[str, np.ndarray], str]],
                 version=None, weight_layout="auto"):
        self.shards = list(shard_dicts_or_paths)
        self.version = version
        if weight_layout not in ("auto", "flax", "torch"):
            raise ValueError(f"weight_layout must be auto/flax/torch, got {weight_layout!r}")
        self.weight_layout = weight_layout

    def _get(self, i) -> Dict[str, np.ndarray]:
        s = self.shards[i]
        if isinstance(s, (str, os.PathLike)):
            s = load_state_file(os.fspath(s))
            self.shards[i] = s
        return s

    def __len__(self):
        return len(self.shards)

    def _layout_of(self, sd: Dict[str, np.ndarray]) -> str:
        if self.weight_layout != "auto":
            return self.weight_layout
        names = list(sd)
        if any(n.endswith("kernel") for n in names):
            return "flax"
        if any(n.endswith(("weight", ".weight")) and sd[n].ndim >= 2
               for n in names):
            return "torch"
        return "flax"

    @staticmethod
    def _axis_for(name: str, ndim: int, layout: str = "flax") -> int:
        """Parallel axis of this weight, or -1 for replicated.

        flax kernels are ``[in, out]`` (column-parallel → last dim); torch
        Linear weights are ``[out, in]`` (column-parallel → dim 0). Embedding
        tables are ``[vocab, hidden]`` in both ecosystems."""
        from ..parallel.tp import _COL_PARALLEL, _ROW_PARALLEL
        if ndim < 2:
            return -1  # biases/norm scales replicate (matches tp.heuristic_spec)
        if _QKV.search(name):
            # fused qkv is column-parallel (output dim)
            return 0 if layout == "torch" else ndim - 1
        if _COL_PARALLEL.search(name):
            return 0 if layout == "torch" else ndim - 1  # output dim
        if _ROW_PARALLEL.search(name):
            return ndim - 1 if layout == "torch" else max(0, ndim - 2)  # input dim
        if "embed" in name or "vocab" in name:
            return 0
        return -1  # replicated

    # ------------------------------------------------------------------
    # fused-qkv segment reorder (reference merge/split_query_key_value)
    # ------------------------------------------------------------------

    def _qkv_merge(self, parts: List[np.ndarray], axis: int) -> np.ndarray:
        """version 0: each rank stores ``[q_r; k_r; v_r]`` on the parallel
        axis — split each rank 3-ways and concatenate per segment so the
        merged weight is ``[Q; K; V]``. version 1.0/2.0 interleave per head
        within the rank, so plain rank concatenation is already correct
        (reference state_dict_factory.py:239-252)."""
        if self.version not in (0, "0"):
            return merge_parallel_dim(parts, axis)
        if parts[0].shape[axis] % 3:
            raise ValueError(f"qkv dim {parts[0].shape[axis]} not divisible by 3")
        segs = [np.split(p, 3, axis=axis) for p in parts]
        return np.concatenate(
            [np.concatenate([s[i] for s in segs], axis=axis) for i in range(3)],
            axis=axis)

    def _qkv_split(self, full: np.ndarray, num: int, axis: int) -> List[np.ndarray]:
        if self.version not in (0, "0"):
            return split_parallel_dim(full, num, axis)
        if full.shape[axis] % (3 * num):
            raise ValueError(f"qkv dim {full.shape[axis]} not divisible by 3*{num}")
        q, k, v = np.split(full, 3, axis=axis)
        return [np.concatenate([np.split(t, num, axis=axis)[r] for t in (q, k, v)],
                               axis=axis) for r in range(num)]

    # ------------------------------------------------------------------

    def load(self, mp_world_size: int, mp_rank: int) -> Dict[str, np.ndarray]:
        """The reference's load-time dispatch (``state_dict_factory.py:56``):

        * ckpt degree == run degree → this rank's file as-is
        * ckpt degree  > run degree → merge ``n/mp`` consecutive shards
        * ckpt degree  < run degree → split one shard ``mp/n`` ways
        """
        n = len(self.shards)
        if not 0 <= mp_rank < mp_world_size:
            raise ValueError(f"mp_rank {mp_rank} out of range for world {mp_world_size}")
        if n == mp_world_size:
            return dict(self._get(mp_rank))
        if n > mp_world_size:
            if n % mp_world_size:
                raise ValueError(f"ckpt degree {n} not divisible by run degree {mp_world_size}")
            k = n // mp_world_size
            group = [self._get(i) for i in range(mp_rank * k, (mp_rank + 1) * k)]
            logger.info(f"SDLoader: merging ckpt shards "
                        f"[{mp_rank * k}, {(mp_rank + 1) * k}) -> mp_rank {mp_rank}")
            return SDLoader(group, version=self.version,
                            weight_layout=self.weight_layout).merge()
        if mp_world_size % n:
            raise ValueError(f"run degree {mp_world_size} not divisible by ckpt degree {n}")
        k = mp_world_size // n
        src = self._get(mp_rank // k)
        logger.info(f"SDLoader: splitting ckpt shard {mp_rank // k} "
                    f"{k}-ways -> mp_rank {mp_rank}")
        return SDLoader([src], version=self.version,
                        weight_layout=self.weight_layout).split(k)[mp_rank % k]

    def merge(self) -> Dict[str, np.ndarray]:
        if len(self.shards) == 1:
            return dict(self._get(0))
        first = self._get(0)
        layout = self._layout_of(first)
        out = {}
        for name, w0 in first.items():
            axis = self._axis_for(name, w0.ndim, layout)
            if axis < 0:
                out[name] = w0  # replicated: any rank's copy
            else:
                parts = [self._get(i)[name] for i in range(len(self.shards))]
                if _QKV.search(name):
                    out[name] = self._qkv_merge(parts, axis)
                else:
                    out[name] = merge_parallel_dim(parts, axis)
        return out

    def split(self, num_shards: int) -> List[Dict[str, np.ndarray]]:
        assert len(self.shards) == 1, "split() expects one merged tree"
        full = self._get(0)
        layout = self._layout_of(full)
        outs = [dict() for _ in range(num_shards)]
        for name, w in full.items():
            axis = self._axis_for(name, w.ndim, layout)
            if axis < 0:
                for o in outs:
                    o[name] = w
            else:
                parts = (self._qkv_split(w, num_shards, axis)
                         if _QKV.search(name)
                         else split_parallel_dim(w, num_shards, axis))
                for o, part in zip(outs, parts):
                    o[name] = part
        return outs
