from .engine import DeepSpeedTpuEngine
from .fp8 import Fp8Linear, fp8_matmul
from .mup import make_base_shapes
from .lr_schedules import (LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR,
                           get_lr_schedule)
from .zero_sharding import ZeroShardingPlan
