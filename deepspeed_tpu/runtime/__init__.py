from .engine import DeepSpeedTpuEngine
from .lr_schedules import (LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR,
                           get_lr_schedule)
from .zero_sharding import ZeroShardingPlan
