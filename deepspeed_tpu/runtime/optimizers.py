"""Optimizer construction from config.

Rebuild of the reference's basic-optimizer factory
(``runtime/engine.py:1272 _configure_optimizer`` / ``:1322``): maps the JSON
``optimizer.type`` names (Adam/AdamW/Lamb/Lion/SGD/Adagrad + 1-bit variants)
onto optax gradient transforms. The reference's "fused" CUDA optimizers
(csrc/adam, csrc/lamb, csrc/lion) are covered by the Pallas fused kernels in
``ops/pallas/fused_optimizer.py``; XLA already fuses the optax update chain
into a handful of kernels, so the optax path is the default and the Pallas
path is opt-in for the largest models.
"""

from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import optax

from ..config.config import (ADAGRAD_OPTIMIZER, ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER,
                             LION_OPTIMIZER, MUADAM_OPTIMIZER, MUADAMW_OPTIMIZER,
                             MUSGD_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
                             SGD_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER)
from ..utils.logging import logger

# shared by the host-offload path (engine._build_host_optimizer) so device and
# host lion defaults can never drift
ADAM_DEFAULT_BETAS = (0.9, 0.999)
LION_DEFAULT_BETAS = (0.9, 0.99)


def _pop(params: Dict[str, Any], *names, default=None):
    for n in names:
        if n in params:
            return params[n]
    return default


def build_optimizer(name: Optional[str],
                    params: Optional[Dict[str, Any]] = None,
                    lr_fn: Optional[Callable] = None) -> Tuple[optax.GradientTransformation, float]:
    """Build the base optax transform for config ``optimizer.type``.

    Returns (transform, base_lr). When `lr_fn` (a schedule step->lr) is given
    it is injected so the schedule runs inside the compiled step.
    """
    params = dict(params or {})
    name = (name or ADAMW_OPTIMIZER).lower()
    lr = float(_pop(params, "lr", default=1e-3))
    # None sentinel: lion's conventional default b2 differs (0.99, optax.lion)
    user_betas = _pop(params, "betas", default=None)
    betas = user_betas if user_betas is not None else ADAM_DEFAULT_BETAS
    eps = float(_pop(params, "eps", default=1e-8))
    weight_decay = float(_pop(params, "weight_decay", default=0.0))
    learning_rate = lr_fn if lr_fn is not None else lr

    if name == ADAM_OPTIMIZER:
        # torch Adam applies weight decay as L2 into the gradient
        tx = optax.chain(
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
            optax.scale_by_learning_rate(learning_rate),
        ) if weight_decay else optax.adam(learning_rate, b1=betas[0], b2=betas[1], eps=eps)
    elif name == ADAMW_OPTIMIZER:
        tx = optax.adamw(learning_rate, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay)
    elif name == LAMB_OPTIMIZER:
        tx = optax.lamb(learning_rate, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay)
    elif name == LION_OPTIMIZER:
        b1, b2 = user_betas if user_betas is not None else LION_DEFAULT_BETAS
        tx = optax.lion(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay)
    elif name == SGD_OPTIMIZER:
        momentum = float(_pop(params, "momentum", default=0.0))
        nesterov = bool(_pop(params, "nesterov", default=False))
        tx = optax.chain(
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.sgd(learning_rate, momentum=momentum or None, nesterov=nesterov),
        )
    elif name == ADAGRAD_OPTIMIZER:
        tx = optax.adagrad(learning_rate, eps=eps)
    elif name in (MUADAM_OPTIMIZER, MUADAMW_OPTIMIZER, MUSGD_OPTIMIZER):
        # muP width-scaled LRs (reference runtime/config.py:79-81)
        from .mup import build_mu_optimizer
        tx = build_mu_optimizer(name, params, learning_rate)
    elif name in (ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER):
        # 1-bit optimizers (reference runtime/fp16/onebit/) need the
        # error-compensated compressed allreduce; built in runtime/onebit.py.
        from .onebit import build_onebit_optimizer
        tx = build_onebit_optimizer(name, params, learning_rate)
    else:
        # Fall through to optax by name (reference allows client optimizers)
        factory = getattr(optax, name, None)
        if factory is None:
            raise ValueError(f"Unknown optimizer: {name}")
        logger.info(f"Resolving optimizer '{name}' directly from optax")
        tx = factory(learning_rate)
    return tx, lr
