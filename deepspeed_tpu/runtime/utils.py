"""Runtime utilities (reference ``deepspeed/runtime/utils.py`` — the pieces
with a TPU seam; grad-norm/flatten helpers live in the engine/jnp natively).
"""

import resource

import jax

from ..utils.logging import logger


def see_memory_usage(message: str, force: bool = False, ranks=(0, )) -> dict:
    """Log device + host memory (reference ``runtime/utils.py
    see_memory_usage``: torch.cuda allocated/reserved + psutil RSS). TPU:
    PJRT per-device stats (bytes_in_use / peak_bytes_in_use) + getrusage
    RSS. Returns the numbers so callers can assert on them."""
    stats = {}
    try:
        # local_devices: on a multi-host pod, jax.devices()[0] can be another
        # process's device, whose memory_stats() raises — and this log line
        # matters most on exactly the non-primary host that is OOMing
        dev = jax.local_devices()[0]
        ms = dev.memory_stats() or {}
        stats["device_bytes_in_use"] = int(ms.get("bytes_in_use", 0))
        stats["device_peak_bytes_in_use"] = int(ms.get("peak_bytes_in_use", 0))
        stats["device_bytes_limit"] = int(ms.get("bytes_limit", 0))
    except Exception:  # backends without memory_stats (some CPU builds)
        stats["device_bytes_in_use"] = 0
        stats["device_peak_bytes_in_use"] = 0
        stats["device_bytes_limit"] = 0
    # ru_maxrss is KiB on Linux
    stats["host_max_rss_bytes"] = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss * 1024
    if force or jax.process_index() in ranks:
        gb = 1024 ** 3
        logger.info(
            f"{message} | device in-use "
            f"{stats['device_bytes_in_use'] / gb:.2f} GB "
            f"(peak {stats['device_peak_bytes_in_use'] / gb:.2f} GB, "
            f"limit {stats['device_bytes_limit'] / gb:.2f} GB) | "
            f"host max-RSS {stats['host_max_rss_bytes'] / gb:.2f} GB")
    return stats
