"""Compiler-scheduled ZeRO-3: traced param prefetch/release in the scan.

Reference: ``runtime/zero/stage3.py`` + ``partitioned_param_coordinator.py``
— params live reduce-scattered (1/dp per chip), a coordinator traces module
execution order and issues each parameter's all-gather ahead of first use
(``stage3_prefetch_bucket_size``), releasing it after last use unless it will
be reused within ``stage3_max_reuse_distance``, never holding more than
``stage3_max_live_parameters`` gathered elements. DeepCompile and T3
(PAPERS.md) make the same argument at the compiler level: derive the schedule
from a *trace* of the step, don't hand-order it.

TPU shape of that machinery:

1. **Param store** — the fp32 masters live as the comm planner's
   dtype-homogeneous flat buckets (``comm/bucketing.py``), each 1-D bucket
   sharded over the ZeRO axes so every chip holds exactly 1/dp of the
   elements. Leaves at or under ``stage3_param_persistence_threshold``
   elements stay replicated (the reference's persistent parameters). The
   optimizer state is built OVER the store, so moments are bucket-sharded
   too — per-chip param+optimizer bytes drop ~dp×.

2. **Schedule pass** — ``jax.make_jaxpr`` traces the per-microbatch loss as
   a function of the compute-dtype param leaves; first/last-use equation
   indices per leaf induce per-bucket *gather epochs* (a bucket re-gathers
   when the elements touched between two of its uses exceed
   ``max_reuse_distance`` — releasing in between). Epochs are issued one
   ahead of use (T3 overlap: bucket k+1's all-gather overlaps bucket k's
   compute) unless prefetching would push the gathered-element peak past
   ``max_live_parameters``.

3. **Scheduled interpreter** — the loss jaxpr is re-evaluated equation by
   equation inside the microbatch ``lax.scan``; at each epoch's issue point
   the bucket shard is all-gathered through ``param_gather_bucket`` (int8
   wire when ``zero_quantized_weights``), cast to compute dtype, and sliced
   into its leaves; rebinding at a later epoch is the structural release
   (XLA's liveness ends at the previous binding's last consumer).
   ``param_gather_bucket``'s backward is the bucket reduce-scatter — the
   exact transpose of a tiled all-gather for the fp32 wire — so gradients
   exit 1/dp-sharded with bitwise stage-2 numerics, and the optimizer steps
   on the owned shard only (cross-replica weight-update sharding,
   arxiv 2004.13336).

The schedule governs FORWARD gather placement. Backward re-gathers come from
autodiff: without rematerialization XLA keeps a gathered bucket's residuals
live into backward — combine with ``activation_checkpointing.remat_policy``
or ``zero_governor.governed_layer_scan`` to bound backward liveness too
(docs/zero3.md).
"""

from dataclasses import dataclass, replace
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax.extend.core import Literal
except ImportError:  # pragma: no cover — older jax
    from jax.core import Literal

from ..comm.bucketing import flatten_buckets, param_gather_bucket, plan_buckets
from ..utils.logging import log_dist, logger


# ---------------------------------------------------------------------------
# param store: fp32 masters as ZeRO-sharded flat buckets
# ---------------------------------------------------------------------------


class Zero3StoreMeta:
    """Static description of a bucketed parameter store.

    The store pytree is ``{"buckets": [1-D fp32 arrays, ZeRO-sharded],
    "persistent": [replicated full leaves]}``; this meta maps it back to the
    original param tree: ``layout`` indexes the NON-persistent leaf list
    (``np_idx[k]`` = original leaf index of that list's k-th entry),
    ``p_idx`` the persistent ones.
    """

    def __init__(self, layout, np_idx: Tuple[int, ...], p_idx: Tuple[int, ...],
                 treedef, leaf_structs: Tuple[Any, ...], bucket_size_mb: float,
                 pad_multiple: int):
        self.layout = layout
        self.np_idx = np_idx
        self.p_idx = p_idx
        self.treedef = treedef
        self.leaf_structs = leaf_structs
        self.bucket_size_mb = bucket_size_mb
        self.pad_multiple = pad_multiple

    @property
    def n_leaves(self) -> int:
        return len(self.leaf_structs)

    @property
    def persistent_elements(self) -> int:
        return sum(int(np.prod(self.leaf_structs[i].shape or (1, )))
                   for i in self.p_idx)


def build_store_meta(params, persistent_idx, bucket_size_mb: float,
                     pad_multiple: int) -> Zero3StoreMeta:
    """Plan the bucketed store for ``params`` (arrays or ShapeDtypeStructs).
    Masters are fp32, so bucketing is planned against fp32 leaf structs."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    structs = tuple(jax.ShapeDtypeStruct(tuple(getattr(l, "shape", ())),
                                         jnp.float32) for l in leaves)
    p_set = set(int(i) for i in persistent_idx)
    np_idx = tuple(i for i in range(len(leaves)) if i not in p_set)
    p_idx = tuple(sorted(p_set))
    layout = plan_buckets([structs[i] for i in np_idx], bucket_size_mb,
                          pad_multiple=pad_multiple)
    return Zero3StoreMeta(layout, np_idx, p_idx, treedef, structs,
                          bucket_size_mb, pad_multiple)


def store_from_tree(tree, meta: Zero3StoreMeta):
    """Param tree -> store pytree (pure; jit with the store shardings)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return {"buckets": flatten_buckets([leaves[i] for i in meta.np_idx],
                                       meta.layout) if meta.np_idx else [],
            "persistent": [leaves[i] for i in meta.p_idx]}


def materialize_params(store, meta: Zero3StoreMeta):
    """Store pytree -> full param tree (pure slices/reshapes; under jit the
    SPMD partitioner gathers each sharded bucket where it is consumed —
    this is the resilience fallback the non-scheduled programs use)."""
    leaves: List[Optional[jnp.ndarray]] = [None] * meta.n_leaves
    for k, i in enumerate(meta.p_idx):
        leaves[i] = store["persistent"][k]
    for arr, b in zip(store["buckets"], meta.layout.buckets):
        for s in b.slots:
            leaves[meta.np_idx[s.leaf_index]] = lax.dynamic_slice_in_dim(
                arr, s.offset, s.size).reshape(s.shape)
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def map_store_subtrees(tree, subtree_def, fn, leaf_fn=lambda x: x):
    """Apply ``fn`` to every subtree of ``tree`` whose structure equals
    ``subtree_def`` (optimizer moments mirror the params-like structure);
    other leaves go through ``leaf_fn``. Used to convert optimizer state
    between store form and tree form, and to build its shardings."""
    def is_sub(x):
        return jax.tree_util.tree_structure(x) == subtree_def

    return jax.tree_util.tree_map(lambda x: fn(x) if is_sub(x) else leaf_fn(x),
                                  tree, is_leaf=is_sub)


def store_opt_state_shardings(opt_state_shape, store_shardings, ctx):
    """Shardings for optimizer state built over the store: params-like
    subtrees get the store shardings (bucket moments stay 1/dp-sharded),
    scalar leaves (step counts) replicate."""
    repl = NamedSharding(ctx.mesh, P())
    store_def = jax.tree_util.tree_structure(store_shardings)
    return map_store_subtrees(opt_state_shape, store_def,
                              lambda _: store_shardings, lambda _: repl)


def zero3_store_supported(engine) -> bool:
    """The scheduled stage-3 program engages when: stage 3, the bucketed
    gradient_comm wire is on, pure-DP mesh whose ZeRO axes ARE the dp world
    (no MiCS/hpZ secondary partition), bf16/fp32 (no fp16 loss scaling),
    device optimizer (no offload), no composed tensor-parallel training."""
    cfg = engine._config
    ctx = engine.mesh_ctx
    zp = engine.zero_plan
    dp_axes = tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)
    return (zp.stage >= 3
            and cfg.gradient_comm_config.active
            and not cfg.fp16_enabled
            and not engine._tp_training
            and engine._offload_device == "none"
            and len(dp_axes) >= 1
            and tuple(zp.zero_axes) == dp_axes
            and all(ctx.axis_size(a) == 1
                    for a in ("model", "seq", "expert", "pipe")))


def init_param_store(engine, params):
    """Convert ``params`` (fp32 master tree) into the bucketed store and
    install it as ``engine.params`` (+ shardings + meta). Runs in
    ``_init_state`` BEFORE optimizer init so the optimizer state is built
    over the store (bucket-sharded moments — the stage-1 half of ZeRO-3)."""
    cfg = engine._config
    zc = cfg.zero_config
    gcc = cfg.gradient_comm_config
    ctx = engine.mesh_ctx
    dp_axes = tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)
    w = ctx.axis_size(dp_axes)
    block = int(gcc.quantization_block_size)
    leaves = jax.tree_util.tree_leaves(params)
    thresh = int(zc.param_persistence_threshold or 0)
    persistent_idx = [i for i, l in enumerate(leaves)
                      if int(np.prod(getattr(l, "shape", ()) or (1, ))) <= thresh]
    from .zero_governor import gather_bucket_mb
    eff_mb = gather_bucket_mb(gcc.bucket_size_mb, zc.max_live_parameters,
                              zc.prefetch_bucket_size)
    meta = build_store_meta(params, persistent_idx, eff_mb, w * block)
    store_shardings = engine.zero_plan.param_store_shardings(
        meta.layout, len(meta.p_idx))
    engine.params = jax.jit(lambda t: store_from_tree(t, meta),
                            out_shardings=store_shardings)(params)
    engine.param_shardings = store_shardings
    engine._zero3_store = meta
    total = sum(int(np.prod(s.shape or (1, ))) for s in meta.leaf_structs)
    log_dist(
        f"ZeRO-3 param store: {len(meta.layout.buckets)} buckets "
        f"({sum(b.padded_size for b in meta.layout.buckets)} elements, "
        f"bucket<= {eff_mb:.2f}MB, 1/{w} per chip) + {len(meta.p_idx)} "
        f"persistent leaves ({meta.persistent_elements}/{total} elements "
        f"replicated, threshold {thresh})", ranks=[0])
    return meta


# ---------------------------------------------------------------------------
# schedule pass: trace -> first/last use -> gather epochs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GatherEpoch:
    """One scheduled all-gather of one bucket: issued before equation
    ``issue_at`` (-1 = program start), landed (sliced into leaves) at
    ``first_use``, releasable after ``last_use``."""
    bucket: int
    issue_at: int
    first_use: int
    last_use: int

    @property
    def prefetched(self) -> bool:
        return self.issue_at < self.first_use


@dataclass(frozen=True)
class Zero3Schedule:
    epochs: Tuple[GatherEpoch, ...]
    n_eqns: int
    peak_live_elements: int
    persistent_elements: int
    prefetch_count: int          # epochs issued ahead of first use
    gather_wire_bytes: int       # per microbatch, per chip, fwd tier


def trace_param_uses(closed_jaxpr, n_param_invars: int):
    """(first_use, last_use) equation index per param invar; ``None`` for
    leaves the traced loss never consumes. Outvar uses count as equation
    index ``len(eqns)``."""
    jaxpr = closed_jaxpr.jaxpr
    first: List[Optional[int]] = [None] * n_param_invars
    last: List[Optional[int]] = [None] * n_param_invars
    pos = {v: i for i, v in enumerate(jaxpr.invars[:n_param_invars])}

    def note(v, t):
        i = pos.get(v)
        if i is not None:
            if first[i] is None:
                first[i] = t
            last[i] = t

    for t, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal):
                note(v, t)
    for v in jaxpr.outvars:
        if not isinstance(v, Literal):
            note(v, len(jaxpr.eqns))
    return first, last


def _gather_recv_bytes(elems: int, world: int, tier: str, block: int) -> int:
    """Receive-side wire bytes per chip for one bucket all-gather."""
    recv = elems * (world - 1) // world
    if tier == "int8":
        nb = (elems + block - 1) // block
        return recv + 8 * nb * (world - 1) // world
    if tier == "onebit":
        return recv // 8 + 4 * (world - 1)
    return recv * 4


def _peak_live(epochs, sizes, persistent_elements: int) -> int:
    """Max gathered elements over the program: sweep every issue point; an
    epoch is live on [issue_at, last_use]."""
    peak = 0
    for t in sorted({e.issue_at for e in epochs}):
        live = sum(sizes[e.bucket] for e in epochs
                   if e.issue_at <= t <= e.last_use)
        peak = max(peak, live)
    return peak + persistent_elements


def derive_schedule(layout, np_idx, first, last, n_eqns: int,
                    max_live_parameters: Optional[int],
                    max_reuse_distance: Optional[int],
                    persistent_elements: int, world: int, fwd_tier: str,
                    block: int) -> Zero3Schedule:
    """Per-bucket gather epochs from the traced first/last uses.

    A bucket's use points are the union of its leaves' first/last-use
    equations. The span splits into multiple epochs (release + re-gather)
    wherever the elements of OTHER buckets used strictly between two
    consecutive use points exceed ``max_reuse_distance`` — the reference's
    release rule, measured in the same parameter-element units. Epochs are
    then issued one ahead (epoch j at epoch j-1's first use; the first at
    program start) unless that would push the gathered-element peak past
    ``max_live_parameters`` — the governor budget demotes prefetches
    (latest first) back to gather-at-use."""
    sizes = [b.padded_size for b in layout.buckets]
    bucket_pts = []
    for b in layout.buckets:
        pts = sorted({p for s in b.slots
                      for p in (first[np_idx[s.leaf_index]],
                                last[np_idx[s.leaf_index]]) if p is not None})
        bucket_pts.append(pts)
    reuse = (int(max_reuse_distance)
             if max_reuse_distance and max_reuse_distance > 0 else None)

    def elems_between(bi, lo, hi):
        tot = 0
        for bj, pts in enumerate(bucket_pts):
            if bj != bi and any(lo < p < hi for p in pts):
                tot += sizes[bj]
        return tot

    spans = []  # (bucket, seg_first_use, seg_last_use)
    for bi, pts in enumerate(bucket_pts):
        if not pts:
            continue  # dead bucket: never gathered, grads stay zero
        start = prev = pts[0]
        for p in pts[1:]:
            if reuse is not None and elems_between(bi, prev, p) > reuse:
                spans.append((bi, start, prev))
                start = p
            prev = p
        spans.append((bi, start, prev))
    spans.sort(key=lambda s: (s[1], s[0]))

    epochs = []
    for j, (bi, fu, lu) in enumerate(spans):
        issue = -1 if j == 0 else min(spans[j - 1][1], fu)
        epochs.append(GatherEpoch(bucket=bi, issue_at=issue, first_use=fu,
                                  last_use=lu))
    budget = (int(max_live_parameters)
              if max_live_parameters and max_live_parameters > 0 else None)
    if budget is not None:
        # demote prefetches, latest-issued first, until the peak fits
        for j in range(len(epochs) - 1, -1, -1):
            if _peak_live(epochs, sizes, persistent_elements) <= budget:
                break
            e = epochs[j]
            if e.prefetched:
                epochs[j] = replace(e, issue_at=e.first_use)
        peak = _peak_live(epochs, sizes, persistent_elements)
        if peak > budget:
            logger.warning(
                f"ZeRO-3 schedule: gathered-element peak {peak} exceeds "
                f"stage3_max_live_parameters={budget} even with every "
                f"prefetch demoted — bucket spans overlap structurally; "
                f"lower gradient_comm.bucket_size_mb or scan the layers "
                f"(zero_governor.governed_layer_scan)")
    wire = sum(_gather_recv_bytes(sizes[e.bucket], world, fwd_tier, block)
               for e in epochs)
    return Zero3Schedule(
        epochs=tuple(epochs), n_eqns=n_eqns,
        peak_live_elements=_peak_live(epochs, sizes, persistent_elements),
        persistent_elements=persistent_elements,
        prefetch_count=sum(1 for e in epochs if e.prefetched),
        gather_wire_bytes=wire)


# ---------------------------------------------------------------------------
# scheduled interpreter + step program
# ---------------------------------------------------------------------------


def _eval_scheduled(closed_jaxpr, meta: Zero3StoreMeta,
                    schedule: Zero3Schedule, shards, pers, margs,
                    ax, fwd_tier: str, bwd_tier: str, block: int,
                    compute_dtype):
    """Re-evaluate the traced loss equation by equation, weaving each
    epoch's ``param_gather_bucket`` in at its issue point and slicing the
    gathered bucket into its leaf bindings at its first use. Runs inside
    the microbatch scan inside the manual (shard_map) region."""
    jaxpr = closed_jaxpr.jaxpr
    n_leaves = meta.n_leaves
    env = {}

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for cv, c in zip(jaxpr.constvars, closed_jaxpr.consts):
        env[cv] = c
    param_vars = jaxpr.invars[:n_leaves]
    for v, x in zip(jaxpr.invars[n_leaves:], jax.tree_util.tree_leaves(margs)):
        env[v] = x
    for k, i in enumerate(meta.p_idx):
        env[param_vars[i]] = pers[k].astype(compute_dtype)

    inflight = {}

    def issue(j, e):
        full = param_gather_bucket(shards[e.bucket], ax, fwd_tier, bwd_tier,
                                   block)
        inflight[j] = full.astype(compute_dtype)

    def land(j, e):
        full = inflight.pop(j)
        for s in meta.layout.buckets[e.bucket].slots:
            env[param_vars[meta.np_idx[s.leaf_index]]] = \
                lax.dynamic_slice_in_dim(full, s.offset, s.size).reshape(s.shape)

    issue_at, land_at = {}, {}
    for j, e in enumerate(schedule.epochs):
        issue_at.setdefault(e.issue_at, []).append((j, e))
        land_at.setdefault(e.first_use, []).append((j, e))
    for j, e in issue_at.get(-1, []):
        issue(j, e)
    for t, eqn in enumerate(jaxpr.eqns):
        for j, e in issue_at.get(t, []):
            issue(j, e)
        for j, e in land_at.get(t, []):
            land(j, e)
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        ans = eqn.primitive.bind(*subfuns, *(read(v) for v in eqn.invars),
                                 **bind_params)
        if not eqn.primitive.multiple_results:
            ans = [ans]
        for v, val in zip(eqn.outvars, ans):
            env[v] = val
    for j, e in land_at.get(len(jaxpr.eqns), []):
        land(j, e)  # leaves consumed only by the outvars
    scaled, loss = (read(v) for v in jaxpr.outvars)
    return scaled, loss


def build_zero3_step(engine, apply_step):
    """Compile the scheduled stage-3 train-batch program for ``engine``.

    Same contract as ``grad_comm.build_grad_comm_step`` (the stage<=2
    builder dispatches here for stage 3): returns ``(step_fn, layout)``
    with the fused train-batch signature ``(store, opt_state, scale_state,
    stacked_args, static_kv)``. The program is built lazily on the first
    call — the schedule pass needs the batch shapes to trace the loss."""
    meta = engine._zero3_store
    assert meta is not None, "build_zero3_step requires the ZeRO-3 param store"
    cfg = engine._config
    zc = cfg.zero_config
    gc = cfg.gradient_comm_config
    ctx = engine.mesh_ctx
    mesh = ctx.mesh
    dp_axes = tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)
    ax = dp_axes if len(dp_axes) > 1 else dp_axes[0]
    w = ctx.axis_size(dp_axes)
    gas = engine.gradient_accumulation_steps()
    compute_dtype = engine.compute_dtype
    apply_fn = engine.apply_fn
    loss_fn = engine._loss_fn
    block = int(gc.quantization_block_size)
    # param gathers quantize with zero_quantized_weights (qwZ); the backward
    # reduce-scatter with zero_quantized_gradients (qgZ). fp32 otherwise —
    # the exact transpose, bitwise-matching stage-2's gradient exchange.
    fwd_tier = "int8" if zc.zero_quantized_weights else "fp32"
    bwd_tier = "int8" if zc.zero_quantized_gradients else "fp32"
    layout = meta.layout
    bucket_shardings = engine.zero_plan.bucket_shardings(layout)
    nb, npers = len(layout.buckets), len(meta.p_idx)

    from .engine import _extract_loss
    from .onebit_wire import _smap

    def scaled_loss_c(cparams, margs):
        # traced in COMPUTE dtype: the fp32->compute cast folds into each
        # gather (an upfront tree cast would make every leaf's first use
        # the program start, degenerating the schedule to gather-everything)
        out = apply_fn(cparams, *margs)
        loss = loss_fn(out) if loss_fn is not None else _extract_loss(out)[0]
        return loss.astype(jnp.float32) / gas, loss

    def _arg_spec(leaf):
        shape = getattr(leaf, "shape", ())
        # dim 0 is the microbatch axis; the batch splits on dim 1 (the
        # stage<=2 program's rule, = batch_sharding(stacked=True))
        if len(shape) < 2 or shape[1] % w != 0:
            return P()
        return P(None, ax)

    def _micro_struct(stacked):
        def one(x):
            shape = tuple(x.shape)
            if len(shape) >= 2 and shape[1] % w == 0:
                return jax.ShapeDtypeStruct((shape[1] // w, ) + shape[2:],
                                            x.dtype)
            return jax.ShapeDtypeStruct(shape[1:], x.dtype)

        return jax.tree_util.tree_map(one, stacked)

    def _compile_for(stacked_args):
        margs_struct = _micro_struct(stacked_args)
        cstructs = [jax.ShapeDtypeStruct(s.shape, compute_dtype)
                    for s in meta.leaf_structs]
        closed = jax.make_jaxpr(
            lambda pl, margs: scaled_loss_c(
                jax.tree_util.tree_unflatten(meta.treedef, pl), margs))(
                    cstructs, margs_struct)
        first, last = trace_param_uses(closed, meta.n_leaves)
        schedule = derive_schedule(
            layout, meta.np_idx, first, last, len(closed.jaxpr.eqns),
            zc.max_live_parameters, zc.max_reuse_distance,
            meta.persistent_elements, w, fwd_tier, block)
        engine._zero3_schedule = schedule

        def scheduled_loss(shards, pers, margs):
            return _eval_scheduled(closed, meta, schedule, shards, pers,
                                   margs, ax, fwd_tier, bwd_tier, block,
                                   compute_dtype)

        def region(shards, pers, stacked):
            def micro(carry, margs):
                acc_s, acc_p, loss_sum = carry
                (_, loss), (g_s, g_p) = jax.value_and_grad(
                    scheduled_loss, argnums=(0, 1), has_aux=True)(
                        shards, pers, margs)
                # forward-order fp32 accumulation, same as the stage<=2
                # scan carry (grad-of-scan would accumulate in reverse)
                acc_s = [a + g.astype(jnp.float32)
                         for a, g in zip(acc_s, g_s)]
                acc_p = [a + g.astype(jnp.float32)
                         for a, g in zip(acc_p, g_p)]
                return (acc_s, acc_p,
                        loss_sum + loss.astype(jnp.float32)), None

            init = ([jnp.zeros((b.padded_size // w, ), jnp.float32)
                     for b in layout.buckets],
                    [jnp.zeros(meta.leaf_structs[i].shape, jnp.float32)
                     for i in meta.p_idx],
                    jnp.float32(0.0))
            (acc_s, acc_p, loss_sum), _ = lax.scan(micro, init, stacked)
            # the gather transpose psum_scatters SUMS over workers; the
            # grad semantic is the mean. Persistent grads are local — one
            # boundary psum.
            acc_s = [a / w for a in acc_s]
            acc_p = [lax.psum(a, ax) / w for a in acc_p]
            loss_mean = lax.pmean(loss_sum / gas, ax)
            return loss_mean, acc_s, acc_p

        def step(store, opt_state, scale_state, stacked, static_kv):
            assert not static_kv, \
                "scheduled ZeRO-3 path takes positional batch arrays only"
            in_specs = ([P(ax)] * nb, [P()] * npers,
                        jax.tree_util.tree_map(_arg_spec, stacked))
            out_specs = (P(), [P(ax)] * nb, [P()] * npers)
            fn = _smap(region, mesh, in_specs, out_specs, dp_axes)
            loss, acc_s, acc_p = fn(store["buckets"], store["persistent"],
                                    stacked)
            acc_s = [lax.with_sharding_constraint(b, s)
                     for b, s in zip(acc_s, bucket_shardings)]
            acc = {"buckets": acc_s, "persistent": list(acc_p)}
            new_store, new_opt, _, new_scale_state, overflow, gnorm = \
                apply_step(store, acc, opt_state, scale_state)
            return loss, new_store, new_opt, new_scale_state, overflow, gnorm

        from .loss_scaler import LossScaleState
        repl = NamedSharding(mesh, P())
        jitted = jax.jit(
            step, donate_argnums=(0, 1), static_argnums=(4, ),
            out_shardings=(None, engine.param_shardings,
                           engine.opt_state_shardings,
                           LossScaleState(*engine.scale_state_shardings),
                           repl, repl))
        obs = getattr(engine, "_train_obs", None)
        if (obs is not None
                and engine._config.observability_config.compile_watch):
            jitted = obs.watch_program(jitted, "zero3_scheduled_step")
        log_dist(
            f"ZeRO-3 scheduled step built: {len(schedule.epochs)} gather "
            f"epochs over {nb} buckets ({schedule.prefetch_count} "
            f"prefetched), wire tiers fwd={fwd_tier}/bwd={bwd_tier}, peak "
            f"live {schedule.peak_live_elements} elements "
            f"(budget {zc.max_live_parameters:.3g}), "
            f"{schedule.gather_wire_bytes} gather B/microbatch/chip",
            ranks=[0])
        return jitted

    compiled = {}

    def step_entry(store, opt_state, scale_state, stacked_args, static_kv):
        key = (jax.tree_util.tree_structure(stacked_args),
               tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree_util.tree_leaves(stacked_args)))
        fn = compiled.get(key)
        if fn is None:
            fn = compiled[key] = _compile_for(stacked_args)
        return fn(store, opt_state, scale_state, stacked_args, static_kv)

    # marker: _watch_compiled_fns must not re-wrap this python entry — the
    # inner jit is watched under its own "zero3_scheduled_step" compile key
    step_entry._zero3_scheduled = True
    engine._zero3_schedule = None  # set at first call (per batch shape)
    return step_entry, layout
