"""ZeRO as sharding rules.

TPU-native rebuild of the reference ZeRO machinery:

- stage 1 (``runtime/zero/stage_1_and_2.py:96`` optimizer-state partitioning)
  = optimizer state sharded over the ZeRO axis
- stage 2 (grad partitioning via hook-driven bucketed reduce-scatter,
  ``stage_1_and_2.py:1364 reduce_ipg_grads``)
  = gradient-accumulation buffer sharded over the ZeRO axis; XLA lowers the
  grad psum into reduce-scatter + allgather-on-use
- stage 3 (``runtime/zero/stage3.py`` param partitioning + on-demand
  allgather via the PartitionedParameterCoordinator)
  = parameters sharded over the ZeRO axis; XLA's SPMD partitioner inserts the
  allgathers exactly where the coordinator's prefetch machinery would, with
  its own overlap scheduling
- MiCS (``runtime/zero/mics.py``) = shard over the ``fsdp`` axis while
  replicating over ``data`` (shard-group semantics come from the mesh shape)
- hpZ secondary partition (``partition_parameters.py:1664``) = choosing the
  innermost (intra-ICI-domain) mesh axis as the ZeRO axis

The partitioning rule: each array leaf is sharded along the largest dimension
divisible by the ZeRO-axis size (ties → earliest dim); leaves smaller than
``param_persistence_threshold`` stay replicated (the reference's persistent
parameters, ``parameter_offload.py:239 mark_persistent_parameters``).
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.mesh import MeshContext


def zero_axes_for(ctx: MeshContext) -> Tuple[str, ...]:
    """The mesh axes ZeRO partitions over: the fsdp axis when it is split
    (MiCS/hybrid-shard layout), else the full data-parallel world."""
    if ctx.axis_size("fsdp") > 1:
        return ("fsdp", )
    return tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)


def choose_partition_dim(shape: Sequence[int], axis_size: int,
                         min_size: int = 0) -> Optional[int]:
    """Largest dim divisible by axis_size (earliest wins ties); None if the
    leaf should stay replicated."""
    if axis_size <= 1 or len(shape) == 0:
        return None
    if int(np.prod(shape)) <= min_size:
        return None
    best, best_len = None, -1
    for d, n in enumerate(shape):
        if n % axis_size == 0 and n >= axis_size and n > best_len:
            best, best_len = d, n
    return best


def leaf_spec(shape: Sequence[int], axes: Tuple[str, ...], axis_size: int,
              min_size: int = 0) -> P:
    d = choose_partition_dim(shape, axis_size, min_size)
    if d is None:
        return P()
    spec = [None] * len(shape)
    spec[d] = axes if len(axes) > 1 else axes[0]
    return P(*spec)


def tree_shardings(tree: Any, ctx: MeshContext, axes: Tuple[str, ...],
                   min_size: int = 0):
    """NamedSharding pytree matching `tree`, sharding each leaf by the rule."""
    size = ctx.axis_size(axes) if axes else 1

    def _one(leaf):
        shape = getattr(leaf, "shape", ())
        if size <= 1:
            return NamedSharding(ctx.mesh, P())
        return NamedSharding(ctx.mesh, leaf_spec(shape, axes, size, min_size))

    return jax.tree_util.tree_map(_one, tree)


def replicated_tree(tree: Any, ctx: MeshContext):
    return jax.tree_util.tree_map(lambda _: NamedSharding(ctx.mesh, P()), tree)


def composed_tp_zero_spec(path: str, shape: Sequence[int], ctx: MeshContext,
                          zero_axes: Tuple[str, ...], zero_size: int,
                          min_size: int = 0, logical=None) -> P:
    """Tensor-parallel spec (column/row rules over the ``model`` axis,
    ``parallel/tp.py``) composed with ZeRO: ZeRO shards the largest dim TP
    left free (earliest wins ties, matching ``choose_partition_dim``); when
    no free dim divides, the TP dim is co-sharded by (model, zero) if the
    per-TP-shard extent still divides. Leaves TP doesn't match degrade to
    the plain ZeRO rule — so norm scales, biases and embeddings behave
    exactly as without TP.

    ``logical``: this leaf's flax logical-axis names (t5x-style
    ``nn.with_partitioning`` metadata) — when given, the TP part comes from
    the LOGICAL_RULES table instead of the name heuristics, so custom
    modules whose param names the AutoTP regexes can't match still TP."""
    from ..parallel.tp import heuristic_spec, spec_from_logical
    mp = ctx.axis_size("model")
    if mp > 1 and logical is not None:
        # honor every LIVE mesh axis the rules name (model, expert, ...);
        # an axis may appear once per spec (first dim wins — LOGICAL_RULES
        # maps both 'heads' and 'kv' to model) and only when the dim divides
        raw = tuple(spec_from_logical(logical))[:len(shape)]
        used, tp_l = set(), []
        for d, e in enumerate(raw):
            ok = (e is not None and e not in used
                  and ctx.axis_size(e) > 1 and shape[d] % ctx.axis_size(e) == 0)
            tp_l.append(e if ok else None)
            if ok:
                used.add(e)
        tp = tuple(tp_l)
    elif mp > 1:
        tp = tuple(heuristic_spec(path, shape, mp))
    else:
        tp = ()
    spec = list(tp) + [None] * (len(shape) - len(tp))
    if not zero_axes or zero_size <= 1 or int(np.prod(shape)) <= min_size:
        return P(*spec)
    zax = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    free = [d for d in range(len(shape))
            if spec[d] is None and shape[d] % zero_size == 0
            and shape[d] >= zero_size]
    if free:
        d = max(free, key=lambda i: (shape[i], -i))
        spec[d] = zax
        return P(*spec)
    for d in sorted((i for i in range(len(shape)) if spec[i] is not None),
                    key=lambda i: -shape[i]):
        cur = spec[d] if isinstance(spec[d], tuple) else (spec[d], )
        taken = int(np.prod([ctx.axis_size(a) for a in cur]))
        if shape[d] % (taken * zero_size) == 0:
            spec[d] = cur + tuple(zero_axes)
            break
    return P(*spec)


def tree_shardings_tp_zero(tree: Any, ctx: MeshContext,
                           zero_axes: Tuple[str, ...], min_size: int = 0,
                           logical_axes: Any = None):
    """NamedSharding pytree composing TP (model axis) with ZeRO sharding.
    Works for params AND optimizer state: the AutoTP name heuristics match
    by substring, and optimizer-state paths (``.../mu/model/layers_0/...``)
    embed the param path, so moments shard exactly like their weights.
    ``logical_axes``: optional pytree of per-leaf logical-name tuples
    (matching ``tree``'s structure) that overrides the name heuristics."""
    from ..parallel.tp import path_str
    zsize = ctx.axis_size(zero_axes) if zero_axes else 1

    def _one(path, leaf, logical=None):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(ctx.mesh, composed_tp_zero_spec(
            path_str(path), shape, ctx, zero_axes, zsize, min_size,
            logical=logical))

    if logical_axes is not None:
        # map over the LOGICAL tree (its tuple/None entries are leaves by
        # is_leaf; as the first tree they never get descended into) with the
        # param tree alongside
        return jax.tree_util.tree_map_with_path(
            lambda path, logical, leaf: _one(path, leaf, logical),
            logical_axes, tree,
            is_leaf=lambda x: x is None or isinstance(x, tuple))
    return jax.tree_util.tree_map_with_path(_one, tree)


class ZeroShardingPlan:
    """Resolved sharding plan for a given ZeRO stage.

    Attributes are NamedSharding pytrees (built lazily against example
    pytrees) for params / grads(accumulation buffer) / optimizer state.
    """

    def __init__(self, ctx: MeshContext, stage: int, param_persistence_threshold: int = 0,
                 tp: bool = False, logical_axes: Any = None):
        self.ctx = ctx
        self.stage = stage
        self.zero_axes = zero_axes_for(ctx) if stage > 0 else ()
        self.param_persistence_threshold = param_persistence_threshold
        # native TP training (config tensor_parallel): every pytree the plan
        # places gets the column/row model-axis sharding composed in — TP
        # applies at EVERY stage (that is its memory/compute point), ZeRO
        # keeps its stage gates for which trees it shards
        self.tp = tp and ctx.axis_size("model") > 1
        # optional t5x-style logical-axis metadata (per-leaf name tuples,
        # param-tree structure): overrides the AutoTP name heuristics for
        # params/grads; optimizer state (different tree structure) falls
        # back to the path heuristics
        self.logical_axes = logical_axes

    def param_shardings(self, params):
        if self.tp:
            zaxes = self.zero_axes if self.stage >= 3 else ()
            return tree_shardings_tp_zero(params, self.ctx, zaxes,
                                          min_size=self.param_persistence_threshold,
                                          logical_axes=self.logical_axes)
        if self.stage >= 3 and self.zero_axes:
            return tree_shardings(params, self.ctx, self.zero_axes,
                                  min_size=self.param_persistence_threshold)
        return replicated_tree(params, self.ctx)

    def grad_shardings(self, params):
        """Sharding of the gradient-accumulation buffer (stage>=2 sharded)."""
        if self.tp:
            return tree_shardings_tp_zero(
                params, self.ctx, self.zero_axes if self.stage >= 2 else (),
                logical_axes=self.logical_axes)
        if self.stage >= 2 and self.zero_axes:
            return tree_shardings(params, self.ctx, self.zero_axes)
        return replicated_tree(params, self.ctx)

    def _logical_by_suffix(self):
        """{param-path-tuple: logical-names} for suffix lookup: optimizer
        moments embed the param subtree (``.../mu/<param path>``), so the
        LONGEST param path that suffixes an opt leaf's path carries that
        leaf's logical metadata — moments then shard exactly like their
        weights even when the param names match no AutoTP regex."""
        if self.logical_axes is None:
            return None
        flat = {}
        for path, names in jax.tree_util.tree_flatten_with_path(
                self.logical_axes,
                is_leaf=lambda x: x is None or isinstance(x, tuple))[0]:
            key = tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
            flat[key] = names
        return flat

    def opt_state_shardings(self, opt_state, params=None):
        """Stage>=1: shard every optimizer-state leaf that matches a
        partitionable shape; scalars (count, loss scale) stay replicated."""
        if self.tp:
            zaxes = self.zero_axes if self.stage >= 1 else ()
            suffix_map = self._logical_by_suffix()
            if not suffix_map:
                return tree_shardings_tp_zero(opt_state, self.ctx, zaxes)
            from ..parallel.tp import path_str
            zsize = self.ctx.axis_size(zaxes) if zaxes else 1

            def _one(path, leaf):
                keys = tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                             for k in path)
                logical = None
                for start in range(len(keys)):  # longest suffix wins
                    if keys[start:] in suffix_map:
                        logical = suffix_map[keys[start:]]
                        break
                return NamedSharding(self.ctx.mesh, composed_tp_zero_spec(
                    path_str(path), getattr(leaf, "shape", ()), self.ctx,
                    zaxes, zsize, logical=logical))

            return jax.tree_util.tree_map_with_path(_one, opt_state)
        if self.stage >= 1 and self.zero_axes:
            return tree_shardings(opt_state, self.ctx, self.zero_axes)
        return replicated_tree(opt_state, self.ctx)

    def bucket_shardings(self, layout):
        """Shardings for the FLAT gradient buckets of a comm plan
        (``comm/bucketing.py BucketLayout``): stage>=2 shards each 1-D bucket
        over the ZeRO axes — the bucketed reduce-scatter's output lands
        directly on each worker's shard and stays there (XLA gathers per-leaf
        on use, exactly where stage-2's allgather-on-use happens); stage<2
        buckets are replicated (pure-DP allreduce semantics). Buckets are
        planned with ``pad_multiple`` = dp world so the split always divides.
        """
        zaxes = self.zero_axes if self.stage >= 2 else ()
        size = self.ctx.axis_size(zaxes) if zaxes else 1
        out = []
        for b in layout.buckets:
            if size > 1 and b.padded_size % size == 0:
                out.append(NamedSharding(
                    self.ctx.mesh, P(zaxes if len(zaxes) > 1 else zaxes[0])))
            else:
                out.append(NamedSharding(self.ctx.mesh, P()))
        return out

    def param_store_shardings(self, layout, n_persistent: int):
        """Shardings for the ZeRO-3 bucketed parameter STORE
        (``runtime/zero3_schedule.py``): the fp32 masters live as flat
        1-D buckets sharded over the ZeRO axes — 1/dp of every parameter
        per chip, the stage-3 residency the reference keeps in
        ``param.ds_tensor`` — while persistent (small) leaves replicate.
        """
        repl = NamedSharding(self.ctx.mesh, P())
        return {"buckets": list(self.bucket_shardings(layout)),
                "persistent": [repl] * n_persistent}

    def batch_sharding(self, batch, stacked: bool = False):
        """Batch is sharded over the full data-parallel world on dim 0
        (``stacked=True``: dim 0 is a microbatch axis; shard dim 1)."""
        dp = tuple(a for a in ("data", "fsdp") if self.ctx.axis_size(a) > 1)
        dim = 1 if stacked else 0

        def _one(leaf):
            shape = getattr(leaf, "shape", ())
            if not dp or len(shape) <= dim or shape[dim] % self.ctx.axis_size(dp) != 0:
                return NamedSharding(self.ctx.mesh, P())
            spec = (None, ) * dim + (dp if len(dp) > 1 else dp[0], )
            return NamedSharding(self.ctx.mesh, P(*spec))

        return jax.tree_util.tree_map(_one, batch)
