"""ZeRO-3 live-parameter memory governor.

Reference: ``runtime/zero/config.py:205-228`` (``stage3_max_live_parameters``,
``stage3_max_reuse_distance``) + ``partitioned_param_coordinator.py:262``
(the prefetch budget: gather ahead only while the live gathered elements stay
under ``max_live_parameters``).

TPU shape of the problem: under ZeRO-3 the params are fsdp-sharded and XLA
inserts the gathers. XLA's scheduler already minimizes live ranges for an
unrolled graph, but it is *free* to hoist every gather to the program start
when latency-hiding wins — there is no hard ceiling. The deterministic,
compiler-proof ceiling is STRUCTURAL: run the layer stack as a ``lax.scan``
over chunks, so at any instant only one chunk's params can exist gathered
(the scan body is the reuse scope; ``jax.checkpoint`` on the body extends the
same ceiling through the backward pass, which re-gathers per chunk instead of
keeping everything alive from forward). Chunk size is derived from the
config's ``max_live_parameters`` — the same knob, honored structurally.

``governed_layer_scan`` is the utility for raw stacked-param layer lists;
the flagship Llama model realizes the same ceiling through its ``nn.scan``
path — ``LlamaConfig.with_live_param_budget(max_live)`` derives
``scan_chunk_size`` from the budget via :func:`chunk_size_for`. The engine
warns at init when a ZeRO-3 model exceeds the budget without a scan-governed
layout.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def per_layer_elements(stacked_params) -> int:
    """Elements of ONE layer of a stacked [L, ...] params pytree."""
    return int(sum(np.prod(l.shape[1:]) for l in jax.tree_util.tree_leaves(stacked_params)))


def chunk_size_for(n_layers: int, per_layer_elems: int,
                   max_live_parameters: Optional[int]) -> int:
    """Largest divisor of n_layers whose chunk stays under the budget.

    A chunk's params are gathered while it computes and again during its
    backward recompute, so the budget covers one chunk (reference semantics:
    max_live_parameters bounds the coordinator's in-flight gather set).
    """
    if not max_live_parameters or per_layer_elems <= 0:
        return 1
    want = max(1, int(max_live_parameters // per_layer_elems))
    best = 1
    for c in range(1, min(want, n_layers) + 1):
        if n_layers % c == 0:
            best = c
    return best


def gather_bucket_mb(bucket_size_mb: float,
                     max_live_parameters: Optional[int] = None,
                     prefetch_bucket_size: Optional[int] = None,
                     itemsize: int = 4) -> float:
    """Effective bucket budget (MB) for the scheduled ZeRO-3 param store.

    The schedule keeps at most two bucket epochs in flight (current + one
    prefetched), so a bucket may not exceed half ``max_live_parameters``;
    the reference's ``stage3_prefetch_bucket_size`` caps one in-flight
    gather directly. Both are element counts — converted at ``itemsize``
    (fp32 masters). The defaults (1e9 / 5e7 elements) are far above the
    25MB comm bucket, so out of the box this is a no-op.
    """
    cap: Optional[int] = None
    if max_live_parameters and max_live_parameters > 0:
        cap = int(max_live_parameters) // 2
    if prefetch_bucket_size and prefetch_bucket_size > 0:
        cap = min(cap, int(prefetch_bucket_size)) if cap is not None \
            else int(prefetch_bucket_size)
    if cap is None:
        return bucket_size_mb
    cap_mb = max(cap * itemsize / 2**20, 1 / 2**20)
    return min(bucket_size_mb, cap_mb)


def governed_layer_scan(layer_apply: Callable,
                        stacked_params,
                        x,
                        *args,
                        max_live_parameters: Optional[int] = None,
                        remat: bool = True):
    """Apply L stacked homogeneous layers to ``x`` with a hard gathered-params
    ceiling of one chunk (chunk sized from ``max_live_parameters``).

    Args:
      layer_apply(layer_params, x, *args) -> x: one layer.
      stacked_params: pytree with leading layer dim [L, ...] on every leaf.
      max_live_parameters: element budget (reference
        ``stage3_max_live_parameters``); None = one layer per step.
      remat: checkpoint each chunk so the backward also re-gathers per chunk
        instead of retaining forward gathers (the ZeRO-3 + activation
        checkpointing combo the reference recommends for big models).
    """
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    chunk = chunk_size_for(L, per_layer_elements(stacked_params), max_live_parameters)
    n_chunks = L // chunk

    chunked = jax.tree_util.tree_map(
        lambda p: p.reshape(n_chunks, chunk, *p.shape[1:]), stacked_params)

    def chunk_body(h, chunk_params):
        def one(h, lp):
            return layer_apply(lp, h, *args), None

        def run(h, cp):
            out, _ = jax.lax.scan(one, h, cp)
            return out

        f = jax.checkpoint(run) if remat else run
        return f(h, chunk_params), None

    out, _ = jax.lax.scan(chunk_body, x, chunked)
    return out
