"""DeepSpeedTpuEngine — the core training engine.

Rebuild of reference ``runtime/engine.py:182 DeepSpeedEngine`` with the same
contract — ``forward`` (:1838) / ``backward`` (:1977) / ``step`` (:2176) /
``save_checkpoint`` (:3109) / ``load_checkpoint`` (:2763) — over a pure,
jitted SPMD train step.

Design (stateful torch-style API over pure JAX):
- `forward(*args)` runs ONE compiled value-and-grad ("fwd_bwd") and caches
  the pending gradients; the returned loss is a live device scalar.  (In
  torch, backward reuses forward's activations; in JAX the only way to get
  that without recompute is to take the grad at forward time. Pure-inference
  calls should use `eval_batch`/`module_forward`, which compile forward-only.)
- `backward(loss)` commits the cached gradients into the (ZeRO-sharded)
  accumulation buffer — the analog of the reference's grad-hook bucketed
  reduce (stage_1_and_2.py:897): under SPMD the reduce is emitted by XLA from
  the sharding specs rather than driven by hooks.
- `step()` at a gradient-accumulation boundary runs the compiled apply step:
  fp16 unscale + overflow check + global-norm clip + optimizer update +
  loss-scale update, all fused in one XLA program (reference does this across
  several host-driven kernel launches).

ZeRO stages are *sharding plans* (see ``zero_sharding.py``), not subclasses.
"""

import os
import time
from contextlib import nullcontext
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import comm as dist
from ..checkpoint.engine import (OrbaxCheckpointEngine, CheckpointCorruptionError,
                                 find_latest_valid_checkpoint, prune_checkpoints,
                                 read_latest_tag, verify_checkpoint,
                                 write_latest_tag)
from ..utils.fault_injection import get_fault_injector
from ..comm.mesh import get_mesh_context, mesh_is_initialized
from ..config import DeepSpeedTpuConfig
from ..utils.logging import logger, log_dist
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, BACKWARD_MICRO_TIMER, FORWARD_GLOBAL_TIMER,
                           FORWARD_MICRO_TIMER, STEP_GLOBAL_TIMER, STEP_MICRO_TIMER,
                           NoopTimer, SynchronizedWallClockTimer, ThroughputTimer)
from .loss_scaler import LossScalerConfig, has_overflow
from .lr_schedules import get_lr_schedule
from .optimizers import build_optimizer
from .zero_sharding import ZeroShardingPlan

try:
    import flax.linen as nn
    _HAS_FLAX = True
except ImportError:  # pragma: no cover
    _HAS_FLAX = False


def _tree_where(cond, a, b):
    return jax.tree_util.tree_map(lambda x, y: jnp.where(cond, x, y), a, b)


def _as_apply_fn(model) -> Callable:
    """Accept a flax Module, (module, method) or raw apply callable."""
    if _HAS_FLAX and isinstance(model, nn.Module):

        def apply_fn(params, *args, **kwargs):
            # "aux_loss" is the contract for modules that sow auxiliary
            # training losses (MoE router load-balancing — reference
            # sharded_moe.py l_aux): sown scalars are ADDED to a scalar
            # model loss; logits outputs pass through untouched
            out, mods = model.apply({"params": params}, *args, **kwargs,
                                    mutable=["aux_loss"])
            aux = jax.tree_util.tree_leaves(mods.get("aux_loss", {}))
            if aux and hasattr(out, "ndim") and out.ndim == 0:
                out = out + sum(jnp.sum(a) for a in aux)
            return out

        return apply_fn
    if callable(model):
        return model
    raise TypeError(f"model must be a flax Module or callable apply_fn, got {type(model)}")


def _split_static_kwargs(kwargs):
    """Split kwargs into (traced, static): plain Python int/bool/str kwargs are
    treated as *static* jit arguments (one cached compile per value). This is
    the contract that lets schedule-driven shape knobs — random-LTD keep
    counts, curriculum seqlens — flow through the compiled step."""
    traced, static = {}, []
    for k, v in kwargs.items():
        if isinstance(v, (bool, int, str)) and not hasattr(v, "shape"):
            static.append((k, v))
        else:
            traced[k] = v
    return traced, tuple(sorted(static))


def _extract_loss(out):
    """Contract: model returns loss, (loss, aux) or dict with 'loss'."""
    if isinstance(out, tuple):
        return out[0], out[1] if len(out) > 1 else None
    if isinstance(out, dict):
        return out["loss"], out
    return out, None


def host_fetch(x):
    """The engine's ONE device→host fetch point. Every steady-state transfer
    the engine itself initiates (window drains, offload scalars, get_loss)
    routes through here, so the async-pipeline trace test can monkeypatch a
    single seam to count/forbid host syncs — JAX's transfer guard does not
    fire on implicit conversions under the CPU backend, so an
    instrumentation seam is the portable way to prove "zero per-step
    syncs"."""
    return jax.device_get(x)


class _AsyncStepWindow:
    """Bounded in-flight window of un-fetched per-step device scalars
    (async_pipeline tentpole: windowed host sync).

    Each optimizer step pushes its (loss, overflow) as LIVE device values —
    no conversion, no barrier — and every ``interval`` in-flight steps the
    engine drains the window with one batched ``host_fetch`` and reconciles
    the deferred host accounting (skipped-step counts, lr-scheduler
    advance, monitor events, steps_per_print logging)."""

    def __init__(self, interval: int):
        self.interval = max(1, int(interval))
        self.entries = []  # (steps, loss, overflow) — device values
        self.comm_steps = 0  # bucketed grad-comm dispatches in this window
        self.t_start = None

    def push(self, steps, loss, overflow):
        if self.t_start is None:
            self.t_start = time.perf_counter()
        self.entries.append((steps, loss, overflow))

    @property
    def in_flight(self) -> int:
        return sum(e[0] for e in self.entries)

    def take(self):
        """Hand back (entries, wall_seconds, comm_steps) and reset."""
        entries, self.entries = self.entries, []
        duration = (time.perf_counter() - self.t_start
                    if self.t_start is not None else 0.0)
        comm_steps, self.comm_steps = self.comm_steps, 0
        self.t_start = None
        return entries, duration, comm_steps


class DeepSpeedTpuEngine:

    @staticmethod
    def _dp_world_from(raw) -> int:
        """dp world = product of (data, fsdp) axes of the configured mesh."""
        import json as _json
        from ..comm.mesh import resolve_axis_sizes, MESH_AXES
        if isinstance(raw, str):
            with open(raw) as f:
                raw = _json.load(f)
        if mesh_is_initialized():
            return get_mesh_context().dp_size
        mesh_cfg = dict(raw.get("mesh", {})) if isinstance(raw, dict) else {}
        mesh_cfg.pop("axis_order", None)
        tp_sz = ((raw.get("tensor_parallel") or {}).get("tp_size")
                 if isinstance(raw, dict) else None)
        if not isinstance(tp_sz, int):
            tp_sz = None  # "auto"/null tolerated like every ConfigModel field
        if tp_sz and tp_sz > 1 and mesh_cfg.get("model", 1) == 1:
            # tensor_parallel.tp_size will create the model axis — the dp
            # estimate (and the batch triangle it validates) must see it.
            # SAME condition as the mesh-creation injection below (model
            # absent OR explicitly 1), or the two dp worlds diverge.
            mesh_cfg["model"] = tp_sz
        # partial specs (e.g. {"model": 2}) leave "data" to absorb leftovers,
        # mirroring MeshContext.create
        if mesh_cfg and all(v != -1 for v in mesh_cfg.values()) and "data" not in mesh_cfg:
            mesh_cfg["data"] = -1
        try:
            sizes = resolve_axis_sizes(jax.device_count(), mesh_cfg or {"data": -1})
        except ValueError:
            return jax.device_count()
        return sizes.get("data", 1) * sizes.get("fsdp", 1)

    def __init__(self,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 collate_fn=None,
                 config=None,
                 mesh_param=None,
                 dont_shard=False,
                 loss_fn=None,
                 **kwargs):
        # Resolve the true data-parallel world BEFORE validating the batch
        # triangle: it depends on the mesh shape (dp = data*fsdp), not on
        # jax.device_count() — a {data:2, model:2} mesh on 4 devices has dp=2.
        if isinstance(config, DeepSpeedTpuConfig):
            self._config = config
        else:
            raw = config if config is not None else {}
            self._config = DeepSpeedTpuConfig(raw, world_size=self._dp_world_from(raw))
        self.module = model
        # multi-output models (reference test_multi_output_model.py): the
        # torch pattern combines the returned losses BETWEEN forward and
        # backward; under the fused step the combiner must live inside the
        # traced program — loss_fn(model_output) -> scalar does exactly that
        self._loss_fn = loss_fn
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_dataloader = None
        self.mpu = mpu
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._pending = None  # (grads, loss) from forward awaiting backward
        self._training = True  # torch module.train()/eval() semantics
        self._last_grad_norm = None
        self.losses = None
        self.last_fwd_spec = None  # abstract fwd arg spec (flops profiler)

        # ---- mesh ----
        if not mesh_is_initialized():
            mc = self._config.mesh_config
            axes = {a: getattr(mc, a) for a in mc.axis_order}
            tp_sz = self._config.tensor_parallel_config.tp_size
            if tp_sz and tp_sz > 1 and axes.get("model", 1) == 1:
                # tensor_parallel.tp_size creates the model axis when the
                # mesh config doesn't name one (inference-config spelling)
                axes["model"] = tp_sz
            elif (tp_sz and tp_sz > 1
                  and axes.get("model", 1) not in (tp_sz, -1)):
                # -1 means the user delegated the size to absorption — only
                # an EXPLICIT different size is a real conflict
                from ..utils.logging import logger as _logger
                _logger.warning(
                    f"tensor_parallel.tp_size={tp_sz} conflicts with mesh "
                    f"model={axes.get('model')} — the mesh axis wins; TP "
                    f"runs at {axes.get('model')}")
            hpz = self._config.zero_config.zero_hpz_partition_size
            if hpz > 1 and axes.get("fsdp", 1) == 1:
                # hpZ (ZeRO++ secondary partition): shard params over the
                # innermost ICI-local axis only; replicate across nodes
                from .zeropp import hpz_mesh_axes
                axes.update(hpz_mesh_axes(jax.device_count(), hpz))
            mics = self._config.zero_config.mics_shard_size
            if mics > 1 and axes.get("fsdp", 1) == 1:
                # MiCS: ZeRO-3 within shard groups, replicate across
                from .mics import mics_mesh_axes
                axes.update(mics_mesh_axes(jax.device_count(), mics))
            if mesh_param is not None:  # reference mesh_param=(dp, sp)
                axes = {"data": mesh_param[0], "seq": mesh_param[1]}
            dist.init_distributed(mesh_axes=axes)
        self.mesh_ctx = get_mesh_context()
        self.dp_world_size = self.mesh_ctx.dp_size
        # pre-initialized mesh may differ from the config's pre-mesh guess
        self._config.reresolve(self.dp_world_size)

        # ---- precision policy ----
        if self._config.bf16_enabled:
            self.compute_dtype = jnp.bfloat16
        elif self._config.fp16_enabled:
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        self.scaler_cfg = LossScalerConfig.from_fp16_config(self._config.fp16_config)
        self._use_loss_scaling = self._config.fp16_enabled
        # data_types.grad_accum_dtype (reference engine.py:938-944): dtype of
        # the gradient-accumulation buffer/scan-carry. None = fp32 (full
        # accumulation precision); bf16 halves the buffer at a documented
        # precision cost. apply_step up-casts to fp32 before the update.
        from ..utils.dtypes import resolve_dtype
        try:
            self.grad_accum_dtype = resolve_dtype(
                self._config.data_types_config.grad_accum_dtype, jnp.float32)
        except ValueError as e:
            raise ValueError(f"data_types.grad_accum_dtype: {e}") from None
        if self.grad_accum_dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
            raise ValueError("data_types.grad_accum_dtype must be "
                             "fp32/bf16/fp16")
        if self.grad_accum_dtype == jnp.float16 and not self._use_loss_scaling:
            # fp16 accumulation saturates at 65504; only the fp16 loss-scaler
            # path runs the overflow check that turns saturation into a
            # skipped step instead of silent inf/NaN params
            raise ValueError("grad_accum_dtype=fp16 requires fp16 training "
                             "(loss scaling + overflow skip); use bf16 or "
                             "fp32 accumulation otherwise")

        # ---- apply fn (+ activation checkpointing) ----
        self.apply_fn = _as_apply_fn(model)
        ac = self._config.activation_checkpointing_config
        if ac.remat_policy:
            policy = getattr(jax.checkpoint_policies, ac.remat_policy, None)
            self.apply_fn = jax.checkpoint(self.apply_fn, policy=policy)

        # ---- lr schedule ----
        self.lr_scheduler = None
        base_lr = (self._config.optimizer_params or {}).get("lr", 1e-3)
        lr_fn = None
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
            lr_fn = getattr(lr_scheduler, "lr_at", None)
        elif self._config.scheduler_name is not None:
            self.lr_scheduler = get_lr_schedule(self._config.scheduler_name,
                                                self._config.scheduler_params or {},
                                                base_lr=base_lr)
            lr_fn = self.lr_scheduler.lr_at

        # ---- optimizer ----
        self._lr_fn = lr_fn
        if optimizer is not None and isinstance(optimizer, optax.GradientTransformation):
            self.base_tx, self._base_lr = optimizer, base_lr
        else:
            self.base_tx, self._base_lr = build_optimizer(self._config.optimizer_name,
                                                          self._config.optimizer_params, lr_fn=lr_fn)
        self.optimizer = self  # engine exposes optimizer-ish API (reference returns the wrapper)

        # ---- ZeRO sharding plan (optionally composed with native TP) ----
        zc = self._config.zero_config
        tpc = self._config.tensor_parallel_config
        tp_requested = tpc.enabled or (tpc.tp_size or 0) > 1
        self._tp_training = tp_requested and self.mesh_ctx.axis_size("model") > 1
        if tp_requested and not self._tp_training:
            from ..utils.logging import logger as _logger
            _logger.warning(
                "tensor_parallel requested but the mesh has no model axis "
                "> 1 — TP sharding disabled (add model to the mesh config "
                "or set tensor_parallel.tp_size)")
        self.zero_plan = ZeroShardingPlan(self.mesh_ctx, zc.stage,
                                          param_persistence_threshold=zc.param_persistence_threshold,
                                          tp=self._tp_training,
                                          logical_axes=kwargs.get("logical_axes"))
        if zc.stage >= 3 and model_parameters is not None:
            # max_live_parameters governor advisory (zero_governor.py): the
            # structural ceiling is scan chunking — warn when the model's
            # unrolled params exceed the configured budget AND the model isn't
            # already scan-governed (embeddings/head stay live regardless)
            scan_governed = bool(getattr(getattr(model, "config", None),
                                         "scan_layers", False))
            n_el = sum(int(np.prod(getattr(p, "shape", ())))
                       for p in jax.tree_util.tree_leaves(model_parameters))
            if n_el > zc.max_live_parameters and not scan_governed:
                from ..utils.logging import logger as _logger
                _logger.warning(
                    f"ZeRO-3: model has {n_el:.3g} elements > "
                    f"stage3_max_live_parameters={zc.max_live_parameters:.3g}. "
                    f"XLA may gather beyond the budget on an unrolled model — "
                    f"use scan_layers (LlamaConfig.with_live_param_budget) or "
                    f"runtime.zero_governor.governed_layer_scan to make the "
                    f"ceiling structural.")

        # ZeRO-Offload: optimizer states on host DRAM or NVMe (reference
        # stage_1_and_2.py cpu-offload path + cpu_adam); frees HBM of the
        # fp32 master + moments at the cost of a device<->host stream per step.
        # ratio < 1.0 = Offload++ Twin-Flow (reference stage3.py:849): the
        # first `ratio` fraction of elements step on host, the rest on device.
        self._offload_device = zc.offload_optimizer_device  # none | cpu | nvme
        self._host_optimizer = None
        self._offload_ratio = (float(zc.offload_optimizer.ratio)
                               if zc.offload_optimizer else 1.0)
        self._host_param_names = set()
        self._device_tx = None

        # ---- persistent compilation cache (async_pipeline tentpole 4:
        # the autotuner-only jax_compilation_cache_dir wiring, promoted) ----
        from .compiler import configure_compile_cache
        configure_compile_cache(self._config.compile_config)

        # ---- async step pipeline (windowed host sync) ----
        apc = self._config.async_pipeline_config
        self._async_window = (_AsyncStepWindow(apc.sync_interval)
                              if apc.enabled else None)

        # ---- training/compiler observability (observability/xla.py +
        # observability/goodput.py): created before the compiled fns so the
        # compile watch can wrap them; the goodput ledger's clock starts
        # here, so construction/auto-resume lands in "restart" ----
        oc = self._config.observability_config
        self._train_obs = None
        self._obs_textfile = None
        if oc.enabled:
            from ..observability.goodput import GoodputLedger
            from ..observability.xla import (TrainInstruments,
                                             install_backend_compile_listener)
            ledger = GoodputLedger() if oc.goodput else None
            self._train_obs = TrainInstruments(ledger=ledger)
            if oc.compile_watch:
                install_backend_compile_listener()
            self._obs_textfile = (oc.textfile
                                  or os.environ.get("DS_TPU_METRICS_TEXTFILE")
                                  or None)

        # ---- state init ----
        if model_parameters is None and _HAS_FLAX and isinstance(model, nn.Module):
            raise ValueError("model_parameters (the flax params pytree) is required")
        self._init_state(model_parameters)

        # ---- compiled steps ----
        self._build_compiled_fns()
        self._watch_compiled_fns()

        # ---- compile() / is_compiled surface (reference engine.py:3665) ----
        from .compiler import attach_compile_api
        attach_compile_api(self)

        # ---- timers / monitor ----
        self.wall_clock_breakdown = self._config.wall_clock_breakdown
        self.timers = SynchronizedWallClockTimer() if self.wall_clock_breakdown else NoopTimer()
        self.tput_timer = ThroughputTimer(
            self._config, batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print,
            # async pipeline: the per-step effects_barrier is the stall the
            # windowed sync exists to remove; the boundary drain is the
            # barrier that keeps multi-step averages honest
            synchronize=self._async_window is None)
        self.monitor = None
        if any([self._config.monitor_config.tensorboard.enabled,
                self._config.monitor_config.wandb.enabled,
                self._config.monitor_config.csv_monitor.enabled,
                self._config.monitor_config.comet.enabled]):
            from ..monitor.monitor import MonitorMaster
            self.monitor = MonitorMaster(self._config.monitor_config)

        # flops profiler (reference engine.py flops_profiler hook)
        self.flops_profiler = None
        self._flops_auto_active = False  # session opened by the auto-hook
        if self._config.flops_profiler_config.enabled:
            from ..profiling.flops_profiler.profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(
                model, ds_engine=self,
                recompute_fwd_factor=self._config.flops_profiler_config.recompute_fwd_factor)

        # ---- data efficiency: curriculum + random-LTD (reference
        # engine.py:349-356 scheduler construction, :1877-1883 forward hooks) ----
        self.curriculum_scheduler_legacy = None
        if self._config.curriculum_enabled_legacy:
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler
            self.curriculum_scheduler_legacy = CurriculumScheduler(
                self._config.curriculum_params_legacy)
        self.random_ltd_scheduler = None
        routing = (self._config.data_efficiency_config or {}).get("data_routing", {})
        if routing.get("enabled") and routing.get("random_ltd", {}).get("enabled", False):
            from .data_pipeline.data_routing import RandomLTDScheduler
            self.random_ltd_scheduler = RandomLTDScheduler(routing)
        # inject the LTD keep-count into models that declare the kwarg (the
        # reference mutates wrapped layers in place; functional models take it
        # as an argument instead — each annealing level is one cached compile)
        self._ltd_kwarg = False
        if self.random_ltd_scheduler is not None:
            import inspect
            try:
                sig = inspect.signature(model.__call__ if _HAS_FLAX
                                        and isinstance(model, nn.Module) else model)
                self._ltd_kwarg = "random_ltd_keep" in sig.parameters
            except (TypeError, ValueError):
                pass

        self.checkpoint_engine = OrbaxCheckpointEngine()
        dist.configure(deepspeed_config=self._config)

        # training data loader (reference deepspeed_io, engine.py:1743)
        if training_data is not None:
            from .dataloader import DeepSpeedDataLoader
            # the host-global batch: per-device micro batch * dp world (the
            # loader yields global arrays that batch_sharding splits over dp)
            self.training_dataloader = DeepSpeedDataLoader(
                training_data,
                batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
                collate_fn=collate_fn,
                sampler=self._build_curriculum_sampler(training_data))
            if apc.enabled and apc.prefetch_depth > 0:
                # device-side input prefetch (async_pipeline tentpole 1):
                # the next N batches' host→device transfers dispatch while
                # the current step runs; the train paths' device_put on an
                # already-sharded batch is a no-op
                from .dataloader import PrefetchingLoader
                self.training_dataloader = PrefetchingLoader(
                    self.training_dataloader, self._prefetch_put,
                    apc.prefetch_depth)

        # ---- resilience: preemption autosave, anomaly sentry, auto-resume
        # (after the dataloader so auto-resume can restore sampler state) ----
        self._init_resilience()

        if self._train_obs is not None:
            # everything up to here — construction, compile-cache setup,
            # auto-resume — is "restart" time; anchor the step clock so the
            # first step's sample measures the step, not engine init
            if self._train_obs.ledger is not None:
                self._train_obs.ledger.mark("restart")
            self._train_obs.start_clock()

        log_dist(
            f"DeepSpeedTpuEngine ready: zero_stage={zc.stage} dtype={self.compute_dtype.__name__} "
            f"mesh={dict(self.mesh_ctx.mesh.shape)} micro_bs={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------

    def _init_state(self, params):
        """Master params fp32 (BF16/FP16 optimizer semantics: reference
        bf16_optimizer.py:34 keeps fp32 master weights), sharded per plan."""
        ctx = self.mesh_ctx
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype=jnp.float32), params)
        # Compiler-scheduled ZeRO-3 (runtime/zero3_schedule.py): when the
        # bucketed wire is on and the mesh qualifies, the fp32 masters live
        # as 1/dp-sharded flat buckets (+ replicated persistent leaves)
        # instead of a leaf tree — the optimizer state below is then built
        # OVER the store, so moments shard identically (params+opt ~dp×
        # smaller per chip). Grads are store-shaped too.
        from .zero3_schedule import init_param_store, zero3_store_supported
        self._zero3_store = None
        self._zero3_schedule = None
        if zero3_store_supported(self):
            init_param_store(self, params)  # sets params/param_shardings/_zero3_store
        else:
            self.param_shardings = self.zero_plan.param_shardings(params)
            self.params = jax.device_put(params, self.param_shardings)

        self.grad_shardings = (self.param_shardings if self._zero3_store is not None
                               else self.zero_plan.grad_shardings(params))
        acc_dtype = self.grad_accum_dtype
        zeros_fn = jax.jit(
            lambda p: jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, acc_dtype), p),
            out_shardings=self.grad_shardings)
        self.grad_acc = zeros_fn(self.params)

        if self._offload_device in ("cpu", "nvme") and self._offload_ratio >= 1.0:
            # no device opt state at all — that's the HBM saving
            self.opt_state = None
            self.opt_state_shardings = None
            self._build_host_optimizer(params)
        elif self._offload_device in ("cpu", "nvme"):
            # Twin-Flow partial offload: split leaves at the `ratio` element
            # boundary (leaf-greedy ≙ reference sub-group split). Host subset:
            # numpy Adam; device subset: the fused optax path. set_to_zero on
            # the host subset keeps those params untouched by the device
            # program — the host step merges its masters back afterwards.
            from .host_offload import flatten_tree, unflatten_like
            # sizes come from array metadata — no device->host transfer here
            flat = flatten_tree(params)
            total = sum(v.size for v in flat.values())
            budget = self._offload_ratio * total
            cum, labels = 0, {}
            for k, v in flat.items():
                if cum < budget:
                    labels[k] = "host"
                    self._host_param_names.add(k)
                    cum += v.size
                else:
                    labels[k] = "device"
            label_tree = unflatten_like(labels, params)
            self._device_tx = optax.multi_transform(
                {"device": self.base_tx, "host": optax.set_to_zero()}, label_tree)
            opt_state_shape = jax.eval_shape(self._device_tx.init, self.params)
            self.opt_state_shardings = self.zero_plan.opt_state_shardings(opt_state_shape)
            self.opt_state = jax.jit(self._device_tx.init,
                                     out_shardings=self.opt_state_shardings)(self.params)
            self._build_host_optimizer(params, subset=self._host_param_names)
            log_dist(f"Twin-Flow partial offload: {cum}/{total} elements "
                     f"({cum/total:.2f}) on host, rest on device", ranks=[0])
        else:
            opt_state_shape = jax.eval_shape(self.base_tx.init, self.params)
            if self._zero3_store is not None:
                from .zero3_schedule import store_opt_state_shardings
                self.opt_state_shardings = store_opt_state_shardings(
                    opt_state_shape, self.param_shardings, self.mesh_ctx)
            else:
                self.opt_state_shardings = self.zero_plan.opt_state_shardings(opt_state_shape)
            self.opt_state = jax.jit(self.base_tx.init,
                                     out_shardings=self.opt_state_shardings)(self.params)

        # Pin every piece of loop-carried state to an explicit NamedSharding —
        # a leaf whose sharding differs between iterations (eager-created
        # scalars come back SingleDeviceSharding) forces a jit recompile every
        # step.
        repl = self.mesh_ctx.replicated()
        self.scale_state = jax.device_put(self.scaler_cfg.initial_state(), repl)
        self.scale_state_shardings = jax.tree_util.tree_map(lambda _: repl,
                                                            tuple(self.scale_state))
        self._one = jax.device_put(jnp.float32(1.0), repl)

    def _build_host_optimizer(self, params, subset=None):
        """ZeRO-Offload host optimizer (numpy Adam ≙ cpu_adam; NVMe moments
        via the pipelined swapper when device=nvme). `subset` restricts it to
        the Twin-Flow host partition."""
        import numpy as _np
        from .host_offload import HostAdamOptimizer, flatten_tree
        op = dict(self._config.optimizer_params or {})
        name = (self._config.optimizer_name or "adamw").lower()
        if name not in ("adam", "adamw", "adagrad", "lion"):
            raise ValueError(
                f"optimizer offload supports adam/adamw/adagrad/lion, got {name}")
        swapper = None
        if self._offload_device == "nvme":
            from .swap_tensor import PipelinedOptimizerSwapper, AioConfig
            oc = self._config.zero_config.offload_optimizer
            nvme_path = str(getattr(oc, "nvme_path", None) or "/tmp/ds_tpu_offload")
            swapper = PipelinedOptimizerSwapper(
                AioConfig(**(self._config._param_dict.get("aio", {}))),
                swap_folder=nvme_path)
        # flatten first, copy only the leaves this optimizer owns (with a
        # Twin-Flow subset, the device partition never crosses the PCIe)
        host_params = {k: _np.asarray(v, _np.float32)
                       for k, v in flatten_tree(params).items()
                       if subset is None or k in subset}
        # hyperparameters mirror the DEVICE path (optimizers.py) exactly so
        # offloaded runs are numerically interchangeable (adagrad has no
        # weight decay in either path; lion's conventional b2 default is 0.99)
        from .optimizers import ADAM_DEFAULT_BETAS, LION_DEFAULT_BETAS
        default_betas = LION_DEFAULT_BETAS if name == "lion" else ADAM_DEFAULT_BETAS
        self._host_optimizer = HostAdamOptimizer(
            host_params,
            lr=float(op.get("lr", 1e-3)),
            betas=tuple(op.get("betas", default_betas)),
            eps=float(op.get("eps", 1e-8)),
            weight_decay=float(op.get("weight_decay", 0.0)),
            mode=name,
            nvme_swapper=swapper,
            lr_fn=(lambda t: self.get_lr()[0]) if self.lr_scheduler is not None else None)

    # ------------------------------------------------------------------
    # compiled fns
    # ------------------------------------------------------------------

    def _build_compiled_fns(self):
        gas = self.gradient_accumulation_steps()
        compute_dtype = self.compute_dtype
        apply_fn = self.apply_fn
        use_scaling = self._use_loss_scaling
        clip = float(self._config.gradient_clipping or 0.0)
        tx = self._device_tx if self._device_tx is not None else self.base_tx
        scaler_cfg = self.scaler_cfg
        self._grad_comm_layout = None  # set when the bucketed program engages

        # Scheduled ZeRO-3 store: every program below sees the bucket store
        # where it used to see the param tree; materialize_params is the
        # slice-back (under jit, GSPMD turns the sharded-bucket reads into
        # per-bucket all-gathers — the resilience fallback; the scheduled
        # train-batch program places those gathers explicitly instead)
        zmeta = getattr(self, "_zero3_store", None)
        if zmeta is not None:
            from .zero3_schedule import materialize_params as _materialize

        # ZeRO++ qwZ/qgZ: explicit int8-wire param gather (fwd) and gradient
        # reduce-scatter (bwd) instead of XLA's implicit bf16 resharding.
        # Under the bucket store the same int8 wire rides the scheduled
        # bucket gathers (param_gather_bucket) — no per-leaf wrap needed.
        zc = self._config.zero_config
        qwz_gather = None
        if zc.zero_quantized_weights and self.zero_plan.stage >= 3 \
                and self.zero_plan.zero_axes and zmeta is None:
            from .zeropp import make_qwz_param_gather
            qwz_gather = make_qwz_param_gather(self.mesh_ctx, self.param_shardings,
                                               qgz=zc.zero_quantized_gradients,
                                               zero_axes=self.zero_plan.zero_axes)

        def loss_from_cparams(cparams, args, kwargs, static_kv, scale):
            out = apply_fn(cparams, *args, **dict(kwargs, **dict(static_kv)))
            if self._loss_fn is not None:
                loss = self._loss_fn(out)
            else:
                loss, _ = _extract_loss(out)
            # scale_loss_by_gas (engine.py:1816) + fp16 loss scaling
            scaled = loss.astype(jnp.float32) / gas
            if use_scaling:
                scaled = scaled * scale
            return scaled, loss

        # param_cast="model": pass fp32 masters straight into apply and let
        # the model's use-site casts (flax `dtype=` convention) down-convert
        # each weight where it is consumed. Under nn.scan this is the
        # structural fix for the whole-model-sized convert_element_type
        # temps an engine-side tree cast creates: the stacked [L, ...] leaf
        # is sliced per scan step and only that chunk is cast. Gradients
        # come back fp32 (cotangent of the fp32 primal) — model-sized, same
        # total as engine-cast's bf16 copy + bf16 grads, without the
        # un-schedulable full-tree cast. qwZ keeps engine casts: its int8
        # wire gather must be followed by an explicit up/down-cast.
        cast_in_model = (self._config.param_cast == "model"
                         and qwz_gather is None)

        def loss_of(params, args, kwargs, static_kv, scale):
            if zmeta is not None:
                params = _materialize(params, zmeta)
            if qwz_gather is not None:
                params = qwz_gather(params)
            if not cast_in_model:
                params = jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype), params)
            return loss_from_cparams(params, args, kwargs, static_kv, scale)

        def value_and_grads(params, args, kwargs, static_kv, scale):
            """((scaled, loss), grads) for one microbatch. With engine-side
            casting, differentiate wrt the COMPUTE-dtype cast of the params,
            not the fp32 masters, when possible: bit-identical values (the
            cast's VJP is an exact bf16->fp32 up-cast, so the fp32 cotangent
            holds the same bf16-representable numbers), but the grad tree is
            STORED at compute dtype — half the gradient HBM at the
            global-norm barrier, where every grad is live at once, and the
            consumers' up-casts fuse into each leaf's optimizer update /
            accumulate. With param_cast="model" the masters go in as-is and
            grads are fp32."""
            if (compute_dtype != jnp.float32 and qwz_gather is None
                    and zmeta is None and not cast_in_model):
                cparams = jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype), params)
                return jax.value_and_grad(loss_from_cparams, has_aux=True)(
                    cparams, args, kwargs, static_kv, scale)
            return jax.value_and_grad(loss_of, has_aux=True)(
                params, args, kwargs, static_kv, scale)

        def fwd_bwd(params, acc, scale, args, kwargs, static_kv):
            # acc dtype = grad_accum_dtype (fp32 default: full accumulation
            # precision across microbatches; bf16 opt-in halves the buffer)
            (scaled, loss), grads = value_and_grads(
                params, args, kwargs, static_kv, scale)
            new_acc = jax.tree_util.tree_map(lambda a, g: a + g.astype(a.dtype), acc, grads)
            return loss, new_acc

        self._fwd_bwd = jax.jit(
            fwd_bwd,
            donate_argnums=(1, ),
            static_argnums=(5, ),
            out_shardings=(None, self.grad_shardings),
        )

        def fwd_only(params, args, kwargs, static_kv):
            if zmeta is not None:
                params = _materialize(params, zmeta)
            if not cast_in_model:
                params = jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype), params)
            return apply_fn(params, *args, **dict(kwargs, **dict(static_kv)))

        self._fwd_only = jax.jit(fwd_only, static_argnums=(3, ))

        def apply_step(params, acc, opt_state, scale_state):
            scale = scale_state.cur_scale if use_scaling else jnp.float32(1.0)
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) / scale, acc)
            overflow = has_overflow(grads) if use_scaling else jnp.bool_(False)

            gnorm = optax.global_norm(grads)
            if clip > 0:
                factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)

            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)

            if use_scaling:
                # skip the step entirely on overflow (reference fused_optimizer.py)
                new_params = _tree_where(overflow, params, new_params)
                new_opt = _tree_where(overflow, opt_state, new_opt)
            new_scale_state = scaler_cfg.update(scale_state, overflow)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return new_params, new_opt, zeroed, new_scale_state, overflow, gnorm

        # On-device grad-norm/clip for the offload paths (async_pipeline
        # tentpole 2): the old host step pulled EVERY gradient leaf over
        # PCIe just to compute the global norm with numpy. This compiled
        # prep program unscales, norms and clips on device — the host sees
        # the (already clipped) host-subset grads plus two scalars.
        self._offload_prep = None
        if self._host_optimizer is not None:
            from .host_offload import flatten_tree
            prep_subset = (frozenset(self._host_param_names)
                           if self._device_tx is not None else None)

            def offload_prep(acc, scale_state):
                scale = (scale_state.cur_scale if use_scaling
                         else jnp.float32(1.0))
                flat = flatten_tree(acc)
                grads = {k: v.astype(jnp.float32) / scale
                         for k, v in flat.items()}
                # left-fold of per-leaf fp32 sums in flat-key order: a
                # deterministic reduction the parity test mirrors on host
                sq = jnp.float32(0.0)
                for k in grads:
                    sq = sq + jnp.sum(jnp.square(grads[k]))
                gnorm = jnp.sqrt(sq)
                # non-finite sum ⇔ the old host path's overflow predicate
                overflow = ~jnp.isfinite(sq)
                if clip > 0:
                    factor = jnp.where(
                        overflow, jnp.float32(1.0),
                        jnp.minimum(1.0, clip / (gnorm + 1e-6)))
                    grads = {k: g * factor for k, g in grads.items()}
                out = {k: g for k, g in grads.items()
                       if prep_subset is None or k in prep_subset}
                return out, overflow, gnorm

            self._offload_prep = jax.jit(offload_prep)

        from .loss_scaler import LossScaleState
        scale_out = LossScaleState(*self.scale_state_shardings)
        repl = self.mesh_ctx.replicated()
        if self._host_optimizer is not None and self._device_tx is None:
            # full ZeRO-Offload: the optimizer step happens on host; no device
            # apply program exists (its state would defeat the offload)
            self._apply_step = None
            self._train_step_fused = None
            self._train_steps_fused = None
            self._train_batch_fused = None
            return
        self._apply_step = jax.jit(
            apply_step,
            donate_argnums=(0, 1, 2),
            out_shardings=(self.param_shardings, self.opt_state_shardings, self.grad_shardings,
                           scale_out, repl, repl),
        )

        # gas=1 fast path: fwd+bwd+optimizer fused into ONE XLA program — no
        # grad-accumulation buffer materialized in HBM and one dispatch per
        # step instead of two (the reference necessarily splits these across
        # host-driven kernel launches; under XLA the fusion is free win)
        def train_step(params, opt_state, scale_state, args, kwargs, static_kv):
            scale = scale_state.cur_scale if use_scaling else jnp.float32(1.0)
            (_, loss), grads = value_and_grads(
                params, args, kwargs, static_kv, scale)
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32) / scale, grads)
            overflow = has_overflow(grads) if use_scaling else jnp.bool_(False)
            gnorm = optax.global_norm(grads)
            if clip > 0:
                factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if use_scaling:
                new_params = _tree_where(overflow, params, new_params)
                new_opt = _tree_where(overflow, opt_state, new_opt)
            new_scale_state = scaler_cfg.update(scale_state, overflow)
            return loss, new_params, new_opt, new_scale_state, overflow, gnorm

        self._train_step_fused = jax.jit(
            train_step,
            donate_argnums=(0, 1),
            static_argnums=(5, ),
            out_shardings=(None, self.param_shardings, self.opt_state_shardings,
                           scale_out, repl, repl),
        ) if gas == 1 and self._device_tx is None else None
        # (Twin-Flow needs the materialized grad buffer to snapshot the host
        # subset, so the one-program fused path is off under partial offload)

        # Multi-step fusion: K OPTIMIZER STEPS in one XLA program — a
        # lax.scan whose carry is (params, opt_state, scale_state) and whose
        # xs are K stacked batches. One host dispatch per K steps amortizes
        # the per-dispatch host/relay round trip to nothing; the schedule
        # stays exact because optax's injected lr_fn reads the update count
        # carried in opt_state. HLO size == one step's body (scan compiles
        # the body once), so compile time does not grow with K. The torch
        # reference cannot express this — its optimizer step is host-driven
        # by construction; under XLA it is one more scan.
        def train_steps(params, opt_state, scale_state, stacked_args,
                        stacked_kwargs, static_kv):
            def one(carry, batch):
                p, o, s = carry
                b_args, b_kwargs = batch
                loss, p, o, s, overflow, gnorm = train_step(
                    p, o, s, b_args, b_kwargs, static_kv)
                return (p, o, s), (loss, overflow, gnorm)

            (p, o, s), (losses, overflows, gnorms) = jax.lax.scan(
                one, (params, opt_state, scale_state),
                (stacked_args, stacked_kwargs))
            return losses, p, o, s, overflows, gnorms

        self._train_steps_fused = jax.jit(
            train_steps,
            donate_argnums=(0, 1),
            static_argnums=(5, ),
            out_shardings=(None, self.param_shardings, self.opt_state_shardings,
                           scale_out, repl, repl),
        ) if self._train_step_fused is not None else None

        # 1-bit compressed WIRE program (reference runtime/comm/nccl.py:16):
        # post-warmup steps exchange packed sign bits instead of fp32 grads.
        # Opt-in via optimizer.params.comm_backend_name (the reference's knob).
        self._wire_step = None
        self._wire_freeze_step = 0
        opname = (self._config.optimizer_name or "").lower()
        op = self._config.optimizer_params or {}
        if (opname in ("onebitadam", "onebitlamb") and op.get("comm_backend_name")
                and self._train_step_fused is not None):
            if self.client_optimizer is not None:
                # a client tx has a different opt-state pytree than the wire
                # program's chain — surface the conflict, don't compress
                logger.warning("1-bit wire program disabled: a client optimizer "
                               "was passed to initialize(); gradients exchange "
                               "uncompressed fp32")
            else:
                from .onebit_wire import build_wire_step, wire_supported
                if wire_supported(self):
                    self._wire_step = build_wire_step(self, opname)
                    self._wire_freeze_step = int(op.get("freeze_step", 100000))
                else:
                    logger.warning("1-bit wire program unavailable (its "
                                   "stateful optimizer-side compression needs "
                                   "gas=1, unpartitioned gradients [ZeRO stage "
                                   "0], bf16/fp32, a pure-DP mesh, and no "
                                   "gradient clipping); falling back to fp32 "
                                   "reduce — consider gradient_comm's onebit "
                                   "tier, which composes with ZeRO stages 1-3")

        # gas>1 fused batch: lax.scan over stacked microbatches + optimizer
        # apply, all in ONE XLA program (one dispatch per optimizer step
        # instead of gas+1; the grad-accumulation buffer is a scan carry, and
        # only one microbatch's activations are live at a time)
        def train_batch_steps(params, opt_state, scale_state, stacked_args, static_kv):
            scale = scale_state.cur_scale if use_scaling else jnp.float32(1.0)
            acc_dtype = self.grad_accum_dtype
            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, acc_dtype), params)

            def micro(carry, margs):
                acc, loss_sum = carry
                loss, acc = fwd_bwd(params, acc, scale, margs, {}, static_kv)
                return (acc, loss_sum + loss), None

            (acc, loss_sum), _ = jax.lax.scan(micro, (zeros, jnp.float32(0.0)),
                                              stacked_args)
            outs = apply_step(params, acc, opt_state, scale_state)
            new_params, new_opt, _, new_scale_state, overflow, gnorm = outs
            return (loss_sum / gas, new_params, new_opt, new_scale_state,
                    overflow, gnorm)

        self._train_batch_fused = jax.jit(
            train_batch_steps,
            donate_argnums=(0, 1),
            static_argnums=(4, ),
            out_shardings=(None, self.param_shardings, self.opt_state_shardings,
                           scale_out, repl, repl),
        ) if gas > 1 and self._device_tx is None and self._host_optimizer is None \
            else None

        # Bucketed + quantized gradient collectives with microbatch overlap
        # (gradient_comm config; comm/bucketing.py + grad_comm.py): replaces
        # the implicit GSPMD boundary reduce with explicit per-bucket
        # reduce-scatter/all-gather through the configured wire tier,
        # optionally issued per microbatch inside the scan (overlap_comm).
        gcc = self._config.gradient_comm_config
        if (gcc.active and self._device_tx is None
                and self._host_optimizer is None and self._wire_step is None):
            from .grad_comm import build_grad_comm_step, grad_comm_supported
            if grad_comm_supported(self):
                step_fn, layout = build_grad_comm_step(self, apply_step)
                self._train_batch_fused = step_fn
                self._grad_comm_layout = layout
                # route train_batch through the bucketed program (gas=1 runs
                # as a 1-microbatch scan); the K-step fused scan and the
                # split forward/backward/step API keep the default reduce
                self._train_step_fused = None
                self._train_steps_fused = None
            else:
                logger.warning(
                    "gradient_comm requested but unsupported here (needs a "
                    "pure data-parallel mesh, ZeRO stage <= 3, bf16/fp32, "
                    "device optimizer; the stage-3 scheduled store further "
                    "excludes optimizer offload, composed tensor-parallel "
                    "training, and meshes whose ZeRO axes don't span the "
                    "full dp world); gradients exchange via the default "
                    "GSPMD reduce")

    def _watch_compiled_fns(self):
        """Compile observability: wrap every jitted step program in a
        ``WatchedJit`` so compile vs cache-hit vs retrace is counted per
        compile key and the MFU publisher can cost-analyze each dispatched
        program. Runs after every ``_build_compiled_fns`` (idempotent on
        already-wrapped programs); transparent to the flops profiler and
        the grad-comm path (``WatchedJit`` forwards attribute access)."""
        obs = getattr(self, "_train_obs", None)
        if obs is None or not self._config.observability_config.compile_watch:
            return
        w = obs.watch_program
        self._fwd_bwd = w(self._fwd_bwd, "train_fwd_bwd")
        self._fwd_only = w(self._fwd_only, "eval_fwd")
        self._apply_step = w(self._apply_step, "optimizer_apply")
        if getattr(self, "_offload_prep", None) is not None:
            self._offload_prep = w(self._offload_prep, "offload_prep")
        if getattr(self, "_train_step_fused", None) is not None:
            self._train_step_fused = w(self._train_step_fused,
                                       "train_step_fused")
        if getattr(self, "_train_steps_fused", None) is not None:
            self._train_steps_fused = w(self._train_steps_fused,
                                        "train_steps_fused")
        if getattr(self, "_train_batch_fused", None) is not None \
                and not getattr(self._train_batch_fused, "_zero3_scheduled",
                                False):
            # the scheduled ZeRO-3 entry is a lazy python wrapper; its inner
            # jit is watched at build time under "zero3_scheduled_step"
            self._train_batch_fused = w(self._train_batch_fused,
                                        "train_batch_fused")
        if getattr(self, "_wire_step", None) is not None:
            self._wire_step = w(self._wire_step, "onebit_wire_step")

    # ------------------------------------------------------------------
    # train API (reference engine.py:1838/:1977/:2176)
    # ------------------------------------------------------------------

    def _build_curriculum_sampler(self, training_data):
        """``data_efficiency.data_sampling.curriculum_learning`` → a
        difficulty-gated DeepSpeedDataSampler over the analyzer's metric
        files (reference deepspeed_io consuming data_sampling config;
        ``data_sampling/data_sampler.py:36``). Returns None when disabled.

        Under single-controller SPMD the sampler draws the GLOBAL batch
        (dp_size=1, micro = per-device micro × dp world); the engine's
        batch sharding splits it over devices."""
        ds_cfg = (self._config.data_efficiency_config or {}).get("data_sampling", {})
        cl = ds_cfg.get("curriculum_learning", {})
        if not (ds_cfg.get("enabled", False) and cl.get("enabled", False)):
            return None
        metrics = cl.get("curriculum_metrics", {})
        if len(metrics) != 1:
            raise ValueError(
                "data_sampling.curriculum_learning.curriculum_metrics must "
                f"contain exactly one metric (got {sorted(metrics)}); the "
                "reference's multi-metric clustering is not implemented")
        from .data_pipeline.curriculum_scheduler import CurriculumScheduler
        from .data_pipeline.data_analyzer import load_metric
        from .data_pipeline.data_sampler import DeepSpeedDataSampler
        name, m = next(iter(metrics.items()))
        values = load_metric(m["metric_path"], name)
        if len(values) != len(training_data):
            raise ValueError(
                f"metric '{name}' covers {len(values)} samples but the "
                f"dataset has {len(training_data)} — rerun the data analyzer")
        sched = CurriculumScheduler({
            "curriculum_type": name,
            "min_difficulty": m["min_difficulty"],
            "max_difficulty": m["max_difficulty"],
            "schedule_type": m.get("schedule_type", "fixed_linear"),
            "schedule_config": m.get("schedule_config", {})})
        return DeepSpeedDataSampler(
            total_samples=len(training_data),
            micro_batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
            gradient_accumulation_steps=self.gradient_accumulation_steps(),
            curriculum_scheduler=sched, metric_values=values,
            shuffle=ds_cfg.get("shuffle", True),
            seed=ds_cfg.get("seed", 1234))

    # ------------------------------------------------------------------
    # resilience: preemption autosave, anomaly sentry + rollback
    # ------------------------------------------------------------------

    def _init_resilience(self):
        rc = self._config.resilience_config
        self._resilience = rc
        self._sentry = None
        self._preempted = False
        self.preempt_count = 0
        self._autosave_requested = False
        self._last_good_tag = None
        self._resilience_save_dir = rc.save_dir
        self._signal_prev_handlers = {}
        if not rc.enabled:
            return
        if rc.fault_injection.enabled:
            get_fault_injector().configure(rc.fault_injection)
        from .sentry import AnomalySentry
        self._sentry = AnomalySentry(
            max_consecutive=rc.max_consecutive_anomalies,
            spike_window=rc.loss_spike_window,
            spike_factor=rc.loss_spike_factor,
            spike_min_history=rc.loss_spike_min_history,
            monitor=self.monitor)
        if rc.preempt_save:
            self._install_preempt_handlers()
        if rc.auto_resume and rc.save_dir:
            # scan for the newest checkpoint that passes manifest
            # verification (NOT blindly `latest`: after a crash the pointer
            # may name a torn dir) and resume from it
            path, _ = self.load_checkpoint(rc.save_dir)
            if path is not None:
                log_dist(f"[resilience] auto-resumed from {path} at step "
                         f"{self.global_steps}", ranks=[0])

    def _install_preempt_handlers(self):
        import signal
        for name in self._resilience.preempt_signals:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            try:
                prev = signal.signal(sig, self._on_preempt_signal)
            except (ValueError, OSError):  # not the main thread
                continue
            self._signal_prev_handlers[sig] = prev

    def _remove_preempt_handlers(self):
        import signal
        for sig, prev in getattr(self, "_signal_prev_handlers", {}).items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._signal_prev_handlers = {}

    def _on_preempt_signal(self, signum, frame):
        # async-signal context: set flags only; the save happens at the next
        # step boundary where the engine's state is consistent
        self._preempted = True
        self.preempt_count += 1
        logger.warning(f"[resilience] signal {signum} received; checkpoint "
                       "will be saved at the next step boundary")

    @property
    def preempted(self) -> bool:
        return self.preempt_count > 0

    def _resilience_step_boundary(self, loss=None, overflow=None,
                                  losses_vec=None, overflows_vec=None):
        """Per-optimizer-step resilience hook (all four train paths).

        Sync mode feeds the sentry here; async mode feeds it at the window
        drain (the fetched values already exist there — no extra sync).
        Autosave/preemption saves always run here: ``save_checkpoint`` drains
        the async window itself, so the snapshot is exact either way."""
        rc = self._resilience
        if not rc.enabled:
            return
        fi = get_fault_injector()
        if fi.enabled and fi.fire("train.sigterm") is not None:
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
        if self._sentry is not None and self._async_window is None:
            if losses_vec is not None:
                lv = np.asarray(host_fetch(losses_vec)).ravel()
                ov = (np.asarray(host_fetch(overflows_vec)).ravel()
                      if overflows_vec is not None else np.zeros(len(lv)))
                base = self.global_steps - len(lv)
                obs = [(float(l), bool(o), base + i + 1)
                       for i, (l, o) in enumerate(zip(lv, ov))]
            else:
                l = (None if loss is None
                     else float(np.asarray(host_fetch(loss)).ravel()[-1]))
                o = (bool(host_fetch(overflow))
                     if overflow is not None and self._use_loss_scaling else False)
                obs = [(l, o, self.global_steps)]
            for l, o, s in obs:
                self._sentry.observe(l, o, s)
                if self._sentry.should_rollback:
                    self._rollback_to_last_good()
                    break
        if (rc.autosave_interval_steps and self.global_steps > 0
                and self.global_steps % rc.autosave_interval_steps == 0):
            self._autosave_requested = True
        if self._preempted and rc.preempt_save:
            self._autosave_requested = True
            self._preempted = False  # one save per preemption notice
        if self._autosave_requested and self._resilience_save_dir:
            self._autosave_requested = False
            ok = self.save_checkpoint(self._resilience_save_dir)
            log_dist(f"[resilience] autosave at step {self.global_steps}: "
                     f"{'committed' if ok else 'FAILED'}", ranks=[0])

    def _sentry_observe_window(self, entries, fetched):
        """Async path: feed the sentry from the drain's already-fetched
        (loss, overflow) window, newest-last; roll back at most once."""
        base = self.global_steps
        total = sum(steps for steps, _, _ in entries)
        step = base - total
        for (steps, _, _), (loss_h, ovf_h) in zip(entries, fetched):
            lv = (np.asarray(loss_h).ravel() if loss_h is not None
                  else np.asarray([np.nan] * steps))
            ov = np.asarray(ovf_h).ravel() if ovf_h is not None else np.zeros(steps)
            if len(lv) < steps:
                lv = np.resize(lv, steps)
            if len(ov) < steps:
                ov = np.resize(ov, steps)
            for i in range(steps):
                step += 1
                l = float(lv[i]) if loss_h is not None else None
                self._sentry.observe(l, bool(ov[i]) and self._use_loss_scaling,
                                     step)
                if self._sentry.should_rollback:
                    self._rollback_to_last_good()
                    return

    def _rollback_to_last_good(self) -> bool:
        """Anomaly recovery: restore params/opt-state/counters from the last
        good checkpoint, but KEEP the data sampler's current position — the
        offending data window is skipped, not replayed (replaying it would
        reproduce the same anomaly)."""
        rc = self._resilience
        self._sentry.reset()
        if not rc.rollback or not self._resilience_save_dir:
            logger.warning("[resilience] anomaly threshold hit but rollback "
                           "is disabled or no save_dir is configured")
            return False
        sampler = getattr(self.training_dataloader, "sampler", None) \
            if self.training_dataloader is not None else None
        sampler_sd = sampler.state_dict() \
            if sampler is not None and hasattr(sampler, "state_dict") else None
        tag = self._last_good_tag or \
            find_latest_valid_checkpoint(self._resilience_save_dir)
        if tag is None:
            logger.warning("[resilience] no valid checkpoint to roll back to")
            return False
        try:
            # goodput: the whole excursion (incl. the inner load_checkpoint,
            # whose nested span folds into this one) is "anomaly_rollback"
            with self._obs_span("anomaly_rollback"):
                path, _ = self.load_checkpoint(self._resilience_save_dir,
                                               tag=tag)
        except CheckpointCorruptionError as e:
            logger.error(f"[resilience] rollback target is corrupt: {e}")
            return False
        if path is None:
            return False
        if sampler_sd is not None:
            # load_checkpoint rewound the sampler with everything else;
            # restore its pre-rollback position to skip the bad window
            sampler.load_state_dict(sampler_sd)
        self._sentry.note_rollback(tag, self.global_steps)
        return True

    def _apply_data_efficiency(self, args, kwargs):
        """Per-micro-batch data-efficiency hooks (reference engine.py:1877-1883):
        advance the curriculum and truncate the batch to the current seqlen
        difficulty; advance random-LTD and inject its keep-count. Seqlen
        truncation changes array shapes, so each difficulty level compiles
        once — ``difficulty_step`` bounds the number of distinct programs."""
        fi = get_fault_injector()
        if fi.enabled and fi.fire("train.nan_grads") is not None:
            # poison the micro-batch's float inputs: forward produces a NaN
            # loss, backward NaN grads — the sentry must catch the episode
            def _poison(x):
                if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                    return jnp.full_like(x, jnp.nan)
                return x
            args = jax.tree_util.tree_map(_poison, args)
        if self.curriculum_scheduler_legacy is not None:
            self.curriculum_scheduler_legacy.update_difficulty(self.global_steps + 1)
            if self._config.curriculum_params_legacy.get("curriculum_type") == "seqlen":
                L = int(self.curriculum_scheduler_legacy.get_current_difficulty())
                # the canonical sequence length is axis 1 of the first array
                # arg (input ids); ONLY axes of that exact length are
                # truncated, so (B, F) feature arrays and unrelated dims pass
                # through; (B, S, S) masks get both seq axes cut
                leaves = [x for x in jax.tree_util.tree_leaves(args)
                          if hasattr(x, "ndim") and x.ndim >= 2]
                S = leaves[0].shape[1] if leaves else None

                def trunc(x):
                    if S is None or L >= S or not hasattr(x, "ndim"):
                        return x
                    for axis in (1, 2):
                        if x.ndim > axis and x.shape[axis] == S:
                            x = jax.lax.slice_in_dim(x, 0, L, axis=axis)
                    return x

                args = jax.tree_util.tree_map(trunc, args)
                kwargs = jax.tree_util.tree_map(trunc, kwargs)
        if self.random_ltd_scheduler is not None:
            self.random_ltd_scheduler.update_seq(self.global_steps)
            if self._ltd_kwarg:
                kwargs = dict(kwargs)
                kwargs["random_ltd_keep"] = int(self.random_ltd_scheduler.get_current_seq())
        return args, kwargs

    def train(self, mode: bool = True):
        """Torch-style mode switch (reference engine is an nn.Module). In
        eval mode ``forward()`` runs the grad-free compiled path — a ported
        eval loop that calls ``engine.eval(); engine.forward(batch)`` does
        NOT silently pay a full backward."""
        self._training = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def forward(self, *args, **kwargs):
        """Compute loss AND cache gradients (see module docstring). After
        ``engine.eval()`` this is forward-only (identical to
        ``eval_batch``); ``engine.train()`` restores the fused
        grad-at-forward training path."""
        if not self._training:
            return self.eval_batch(*args, **kwargs)
        if self._pending is not None:
            # forward() accumulates grads at forward time (module docstring);
            # a second forward without backward() would silently contaminate
            # the accumulation buffer — the reference's forward is pure, so
            # ported eval loops must use eval_batch()/module_forward()
            raise RuntimeError(
                "forward() called twice without backward(); for inference/eval "
                "use engine.eval() (then forward() is grad-free), eval_batch() "
                "or module_forward()")
        self.timers(FORWARD_MICRO_TIMER).start()
        scale = self.scale_state.cur_scale if self._use_loss_scaling else self._one
        args, kwargs = self._apply_data_efficiency(args, kwargs)
        kwargs, static_kv = _split_static_kwargs(kwargs)
        args = jax.device_put(args, self.zero_plan.batch_sharding(args))
        kwargs = jax.device_put(kwargs, self.zero_plan.batch_sharding(kwargs))
        loss, new_acc = self._fwd_bwd(self.params, self.grad_acc, scale, args, kwargs,
                                      static_kv)
        # grad_acc was donated; keep the new buffer, commit on backward()
        self.grad_acc = new_acc
        self._pending = loss
        # abstract arg spec for the flops profiler's cost analysis
        self.last_fwd_spec = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype) if hasattr(x, "shape") else x,
            (self.params, self.grad_acc, scale, args, kwargs, static_kv))
        # AFTER the spec records THIS step's shapes (curriculum can resize
        # per step); dispatch above is async, so the timing window still
        # covers the device execution
        self._flops_profile_pre()
        self.timers(FORWARD_MICRO_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss, retain_graph=False, scale_wrt_gas=True):
        """Commit the pending accumulated grads (bookkeeping; compute happened
        fused with forward)."""
        assert self._pending is not None, "backward() called without a preceding forward()"
        self.timers(BACKWARD_MICRO_TIMER).start()
        self._pending = None
        self.losses = loss
        self.micro_steps += 1
        self.timers(BACKWARD_MICRO_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps % self.gradient_accumulation_steps()) == 0

    def _flops_profile_pre(self, step_fn=None, step_args=None, steps: int = 1):
        """Reference engine.py flops-profiler hooks: the engine itself starts
        the profile when global_steps reaches ``profile_step`` — the config
        knob used to be accepted and silently ignored (a user enabling
        ``flops_profiler`` got no output without driving the profiler by
        hand). ``step_fn``/``step_args``: the fused one-program step, whose
        exact compiled cost is recorded (the split path's cost comes from
        ``last_fwd_spec`` inside ``start_profile``). ``steps``: how many
        real optimizer steps the upcoming dispatch covers — a K-step fused
        dispatch must trigger when profile_step falls anywhere inside
        [global_steps, global_steps + K)."""
        fp = self.flops_profiler
        c = self._config.flops_profiler_config
        if (fp is None or fp.started
                or not (self.global_steps <= c.profile_step
                        < self.global_steps + steps)):
            return
        # the fused program already contains fwd+bwd+step: accruing the
        # split-path _fwd_bwd cost too would double the reported flops
        fp.start_profile(skip_engine_cost=step_fn is not None)
        self._flops_auto_active = True
        if step_fn is not None and step_args is not None:
            try:
                fp.profile_fn(step_fn, *step_args)
            except Exception as e:  # noqa: BLE001 — cost analysis best-effort
                logger.debug(f"flops profiler: fused cost analysis skipped: {e}")

    def _flops_profile_post(self):
        fp = self.flops_profiler
        c = self._config.flops_profiler_config
        if (fp is None or not fp.started or self.global_steps <= c.profile_step
                or not getattr(self, "_flops_auto_active", False)):
            # only close sessions the auto-hook opened — a profile the USER
            # started via the manual reference API is theirs to stop/print
            return
        self._flops_auto_active = False
        fp.stop_profile()
        fp.print_model_profile(profile_step=c.profile_step,
                               module_depth=c.module_depth,
                               top_modules=c.top_modules, detailed=c.detailed,
                               output_file=c.output_file,
                               batch_tokens=self.train_batch_size())
        fp.end_profile()

    def step(self, lr_kwargs=None):
        """Optimizer step at gradient-accumulation boundaries (engine.py:2176)."""
        self.timers(STEP_MICRO_TIMER).start()
        if self.is_gradient_accumulation_boundary() and self.micro_steps > 0:
            self.tput_timer.start()
            if self._host_optimizer is not None and self._device_tx is not None:
                overflow, gnorm = self._partial_offload_step()
            elif self._host_optimizer is not None:
                overflow, gnorm = self._host_offload_step()
            else:
                (self.params, self.opt_state, self.grad_acc, self.scale_state, overflow,
                 gnorm) = self._apply_step(self.params, self.grad_acc, self.opt_state,
                                           self.scale_state)
            self._last_grad_norm = gnorm
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            self.tput_timer.stop(global_step=True)
            self._obs_step_mark(1)
            if (self._async_window is not None
                    and self._host_optimizer is None):
                # windowed host sync: overflow stays a device scalar; every
                # per-step host decision (skip accounting, schedule advance,
                # monitor, print cadence) is reconciled at the drain
                self._push_async_step(self.losses, overflow)
            else:
                if self._use_loss_scaling:
                    # host sync only for logging cadence; cheap scalar
                    if bool(overflow):
                        self.skipped_steps += 1
                        log_dist(f"[deepspeed] OVERFLOW! Skipping step. New loss scale: "
                                 f"{float(self.scale_state.cur_scale)}", ranks=[0])
                    else:
                        self._advance_schedule()
                else:
                    self._advance_schedule()
                if self.monitor is not None and self.losses is not None:
                    self.monitor.write_events([("Train/Samples/train_loss", float(self.losses),
                                                self.global_samples)])
                self._publish_registry_events()
                if self._config.steps_per_print and self.global_steps % self._config.steps_per_print == 0:
                    log_dist(
                        f"step={self.global_steps}, skipped={self.skipped_steps}, "
                        f"lr={self.get_lr()}, loss={float(self.losses) if self.losses is not None else None}",
                        ranks=[0])
            self._flops_profile_post()
            self._resilience_step_boundary(loss=self.losses, overflow=overflow)
        self.timers(STEP_MICRO_TIMER).stop()

    def _host_offload_step(self):
        """ZeRO-Offload step, pipelined (reference stage_1_and_2.py cpu-offload
        + cpu_adam + pipelined_optimizer_swapper.py overlap):

        1. the compiled prep program unscales, global-norms and clips ON
           DEVICE (async_pipeline tentpole 2 — no grad leaf crosses PCIe
           for the norm; only the overflow/gnorm scalars do);
        2. async device→host copies for every (clipped) grad leaf kick off
           up front — the per-leaf readbacks below then wait only for their
           own leaf while the rest stream in the background;
        3. the Adam pass updates one leaf at a time and immediately kicks its
           async host→device upload — uploads overlap the remaining leaves'
           host math (double buffering without CUDA streams)."""
        from .host_offload import flatten_tree, unflatten_like
        clipped, overflow_d, gnorm_d = self._offload_prep(self.grad_acc,
                                                          self.scale_state)
        for v in clipped.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        overflow_h, gnorm_h = host_fetch((overflow_d, gnorm_d))
        overflow, gnorm = bool(overflow_h), float(gnorm_h)
        if not overflow:
            flat_s = flatten_tree(self.param_shardings)
            names = list(clipped.keys())
            self._host_optimizer.step_begin()
            new_flat = {}
            for i, k in enumerate(names):
                g = np.asarray(clipped[k])
                p_new = self._host_optimizer.step_param(
                    k, g, prefetch=names[i + 1] if i + 1 < len(names) else None)
                # async dispatch: this upload flies while the next leaf steps
                # (numpy straight to the target sharding — one transfer)
                new_flat[k] = jax.device_put(p_new, flat_s[k])
            self._host_optimizer.step_end()
            self.params = unflatten_like(new_flat, self.params)
        if self._use_loss_scaling:
            self.scale_state = self.scaler_cfg.update(self.scale_state, jnp.bool_(overflow))
        self.grad_acc = jax.tree_util.tree_map(
            lambda g: jax.device_put(jnp.zeros(g.shape, g.dtype), g.sharding),
            self.grad_acc)
        return overflow, gnorm

    def _partial_offload_step(self):
        """Twin-Flow (Offload++) step: snapshot the host-subset grads, kick the
        device-subset program (async XLA dispatch), then run host Adam WHILE
        the device program executes — the overlap the reference gets from CUDA
        streams (blogs/deepspeed-offloadpp/README.md:10) falls out of XLA's
        async dispatch. Finally merge host masters back into the param tree.

        Unscale + global-norm + clip happen ON DEVICE in the compiled prep
        program (async_pipeline tentpole 2) BEFORE the apply program donates
        grad_acc: the host subset arrives over PCIe already clipped, so —
        unlike the old host-side clip — a gradient-clipping config no longer
        forces a device/host serialization point; only fp16 loss scaling
        still syncs one scalar (the host Adam must know whether to skip)."""
        from .host_offload import flatten_tree, unflatten_like
        clipped, overflow_d, _ = self._offload_prep(self.grad_acc,
                                                    self.scale_state)
        for v in clipped.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        # device subset steps in its compiled program (donates grad_acc/opt);
        # host params pass through it unchanged (set_to_zero)
        (params, self.opt_state, self.grad_acc, self.scale_state, overflow,
         gnorm) = self._apply_step(self.params, self.grad_acc, self.opt_state,
                                   self.scale_state)
        overflow_b = (bool(host_fetch(overflow_d))
                      if self._use_loss_scaling else False)
        if not overflow_b:
            # np.asarray blocks only on the host-subset leaves, whose async
            # copies started before the device apply dispatched
            master = self._host_optimizer.step(
                {k: np.asarray(v) for k, v in clipped.items()})
            flat_p = flatten_tree(params)
            flat_s = flatten_tree(self.param_shardings)
            for k in self._host_param_names:
                flat_p[k] = jax.device_put(master[k], flat_s[k])
            params = unflatten_like(flat_p, params)
        self.params = params
        return overflow_b, gnorm

    def _advance_schedule(self):
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()
        from ..observability import get_registry
        get_registry().counter(
            "ds_train_steps_total", "Effective (non-skipped) optimizer steps"
        ).inc()

    def _obs_step_mark(self, steps=1):
        """Per-optimizer-step observability boundary: record the step-wall
        histogram sample(s) and attribute the interval to goodput
        "useful_step". Host-only (one perf_counter + histogram bump) —
        never syncs the device."""
        obs = self._train_obs
        if obs is not None:
            obs.step_mark(steps)

    def _obs_span(self, category):
        """Goodput span for an excursion (checkpoint save/load, rollback,
        host-sync stall); nullcontext when observability is off."""
        obs = getattr(self, "_train_obs", None)
        if obs is not None and obs.ledger is not None:
            return obs.ledger.span(category)
        return nullcontext()

    def _publish_registry_events(self, window_start=None, window_len=None):
        """Registry publish cadence: refresh derived observability views
        (MFU, memory, goodput fraction), fan the registry into the monitor
        bridge (``monitor.registry_events``), and rewrite the Prometheus
        textfile. Async windows pass ``window_start``/``window_len`` so the
        events are stamped at the step the window STARTED on plus an
        explicit length event — stamping the drain-time ``global_steps``
        attributed a whole window's metrics to its last step."""
        if self._train_obs is not None:
            self._train_obs.publish()
        if (self.monitor is not None
                and self._config.monitor_config.registry_events):
            step = self.global_steps if window_start is None else window_start
            self.monitor.write_registry(step, window_len=window_len)
        if self._obs_textfile:
            from ..observability import get_registry
            try:
                get_registry().write_textfile(self._obs_textfile)
            except OSError as e:
                logger.warning(
                    f"observability textfile export to "
                    f"{self._obs_textfile} failed: {e}; disabling")
                self._obs_textfile = None

    # ------------------------------------------------------------------
    # async step pipeline (windowed host sync)
    # ------------------------------------------------------------------

    def _prefetch_put(self, batch):
        """Dispatch one host batch to device, sharded per the mesh (the
        prefetch iterator's put_fn). Transfers are async — this returns
        immediately with arrays whose copies stream in the background."""
        return jax.device_put(batch, self.zero_plan.batch_sharding(batch))

    def prefetch(self, data_iter, depth=None):
        """Wrap any batch iterator in the device-side prefetch
        (async_pipeline tentpole 1): the next ``depth`` batches'
        host→device transfers stay in flight while the current step runs.
        Yields device-resident batches the train paths consume without a
        further transfer."""
        from .dataloader import DevicePrefetchIterator
        if depth is None:
            depth = self._config.async_pipeline_config.prefetch_depth or 2
        return DevicePrefetchIterator(data_iter, self._prefetch_put, depth)

    def _push_async_step(self, loss, overflow, steps=1, sample_base=None):
        """Record one dispatch's un-fetched device scalars (``steps`` > 1 ⇔
        a K-step fused dispatch pushing vectors) and queue its monitor
        events; drain when the window fills."""
        w = self._async_window
        w.push(steps, loss, overflow)
        if self.monitor is not None and loss is not None:
            bs = self.train_batch_size()
            if steps == 1:
                self.monitor.write_events_async(
                    [("Train/Samples/train_loss", loss, self.global_samples)])
            else:
                base = (self.global_samples - (steps - 1) * bs
                        if sample_base is None else sample_base)
                self.monitor.write_events_async(
                    [("Train/Samples/train_loss", loss,
                      [base + i * bs for i in range(steps)])])
        if w.in_flight >= w.interval:
            self._drain_async_window()

    def _drain_async_window(self):
        """Fetch every in-flight step's (loss, overflow) in ONE batched
        device→host transfer and reconcile the deferred host accounting:
        skipped-step counts, lr-scheduler advances (compiled-path lr is
        exact regardless — optax reads the update count carried in
        opt_state; only host-side ``get_lr()`` reporting lags mid-window),
        bucketed-comm traffic banking, monitor flush, steps_per_print."""
        w = self._async_window
        if w is None or not w.entries:
            return
        entries, duration, comm_steps = w.take()
        with self._obs_span("host_sync_stall"):
            # the ONE deliberate device→host block of the window
            fetched = host_fetch([(loss, ovf) for (_, loss, ovf) in entries])
        total_steps, n_overflow, last_loss = 0, 0, None
        for (steps, _, _), (loss_h, ovf_h) in zip(entries, fetched):
            total_steps += steps
            if self._use_loss_scaling:
                a = np.asarray(ovf_h)
                n_overflow += int(a.sum()) if a.ndim else int(bool(a))
            if loss_h is not None:
                l = np.asarray(loss_h)
                last_loss = float(l.ravel()[-1]) if l.ndim else float(l)
        self.skipped_steps += n_overflow
        for _ in range(total_steps - n_overflow):
            self._advance_schedule()
        if n_overflow:
            log_dist(f"[deepspeed] OVERFLOW! {n_overflow} step(s) skipped "
                     f"in the last sync window.", ranks=[0])
        if comm_steps and self._grad_comm_layout is not None:
            from .grad_comm import record_window_traffic
            gcc = self._config.gradient_comm_config
            tier = getattr(gcc.comm_quantization, "value",
                           gcc.comm_quantization)
            record_window_traffic(
                self._grad_comm_layout, self.dp_world_size, str(tier),
                gcc.quantization_block_size, duration, comm_steps,
                op="reduce_scatter")
            self._bank_zero3_gathers(comm_steps)
        if self.monitor is not None:
            self.monitor.flush_events(fetch=host_fetch)
        self._publish_registry_events(
            window_start=self.global_steps - total_steps,
            window_len=total_steps)
        if getattr(self, "_sentry", None) is not None:
            # async-mode sentry feed: the window's values were just fetched
            # in the batched transfer above — zero additional syncs
            self._sentry_observe_window(entries, fetched)
        spp = self._config.steps_per_print
        if spp and (self.global_steps // spp
                    > (self.global_steps - total_steps) // spp):
            log_dist(
                f"step={self.global_steps}, skipped={self.skipped_steps}, "
                f"lr={self.get_lr()}, loss={last_loss}", ranks=[0])

    def get_loss(self):
        """Latest training loss as a host float. Async mode: drains the
        in-flight sync window first (ONE batched fetch — this is the
        documented on-demand sync point), so mid-window calls return the
        newest step's loss, not a stale boundary value. Returns None before
        the first step."""
        self._drain_async_window()
        if self.losses is None:
            return None
        l = np.asarray(host_fetch(self.losses))
        return float(l.ravel()[-1]) if l.ndim else float(l)

    def train_batch(self, data_iter=None):
        """Pipeline-engine-style full batch step (reference pipe/engine.py:337):
        runs gradient_accumulation_steps micro-batches + the optimizer step."""
        # train_batch IS training: restore train mode so an eval loop's
        # engine.eval() doesn't strand the non-fused path (forward would
        # reroute to eval_batch and backward() would fail) — matches the
        # reference, where eval mode never blocks train_batch
        self._training = True
        if self._train_step_fused is not None:
            batch = next(data_iter)
            if not isinstance(batch, tuple):
                batch = (batch, )
            loss = self.fused_train_step(*batch)
            # async mode returns the LIVE device scalar — float() here would
            # reinstate the very per-step barrier the window removes; callers
            # wanting a host number use get_loss() (drains the window)
            return loss if self._async_window is not None else float(loss)
        if self._train_batch_fused is not None:
            return self._run_fused_train_batch(data_iter)
        losses = []
        for _ in range(self.gradient_accumulation_steps()):
            batch = next(data_iter)
            if not isinstance(batch, tuple):
                batch = (batch, )
            loss = self.forward(*batch)
            self.backward(loss)
            self.step()
            losses.append(loss)  # device scalars; convert after the loop so
            # micro-steps pipeline instead of syncing the host every iteration
        if self._async_window is not None:
            return sum(losses) / self.gradient_accumulation_steps()
        return float(sum(float(l) for l in losses)) / self.gradient_accumulation_steps()

    def _run_fused_train_batch(self, data_iter):
        """gas>1 one-program path: pull gas microbatches, stack on a leading
        axis, run the scan-fused program (one dispatch per optimizer step)."""
        gas = self.gradient_accumulation_steps()
        micros = []
        for _ in range(gas):
            batch = next(data_iter)
            if not isinstance(batch, tuple):
                batch = (batch, )
            batch, kw = self._apply_data_efficiency(batch, {})
            assert not kw, "fused gas path takes positional batch arrays only"
            micros.append(batch)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *micros)
        stacked = jax.device_put(
            stacked, self.zero_plan.batch_sharding(stacked, stacked=True))
        step_t0 = time.perf_counter()
        self.tput_timer.start()
        self._flops_profile_pre(self._train_batch_fused,
                                (self.params, self.opt_state, self.scale_state,
                                 stacked, ()))
        (loss, self.params, self.opt_state, self.scale_state, overflow,
         gnorm) = self._train_batch_fused(self.params, self.opt_state,
                                          self.scale_state, stacked, ())
        self._last_grad_norm = gnorm
        self.losses = loss
        self.micro_steps += gas
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self.tput_timer.stop(global_step=True)
        self._obs_step_mark(1)
        if self._async_window is not None:
            # windowed sync: the loss stays a device scalar; comm traffic is
            # banked at the drain against the whole window's wall clock
            # (per-step host timing would itself be the sync we're removing)
            if self._grad_comm_layout is not None:
                self._async_window.comm_steps += 1
            self._push_async_step(loss, overflow)
            self._flops_profile_post()
            self._resilience_step_boundary(loss=loss, overflow=overflow)
            return loss
        if self._use_loss_scaling and bool(overflow):
            self.skipped_steps += 1
        else:
            self._advance_schedule()
        if self.monitor is not None:
            self.monitor.write_events([("Train/Samples/train_loss", float(loss),
                                        self.global_samples)])
        self._publish_registry_events()
        self._flops_profile_post()
        loss_val = float(loss)  # blocks on the dispatch
        if self._grad_comm_layout is not None:
            # per-step wire volume -> CommsLogger/calc_bw_log; the in-trace
            # collectives can't time themselves, so bank the host-measured
            # step wall against the bucketed byte count
            from ..comm.bucketing import record_bucket_traffic
            gcc = self._config.gradient_comm_config
            tier = getattr(gcc.comm_quantization, "value", gcc.comm_quantization)
            record_bucket_traffic(
                self._grad_comm_layout, self.dp_world_size,
                str(tier), gcc.quantization_block_size,
                duration=time.perf_counter() - step_t0, op="reduce_scatter")
            self._bank_zero3_gathers(1)
        self._resilience_step_boundary(loss=loss, overflow=overflow)
        return loss_val

    def _bank_zero3_gathers(self, steps: int):
        """Registry accounting for the scheduled ZeRO-3 param gathers:
        wire bytes actually moved by the bucket all-gathers (post-
        quantization, receive side per chip) and the prefetch-epoch count —
        the schedule is static per compiled program, so ``steps`` optimizer
        steps move exactly ``steps * gas`` microbatch traversals of it."""
        sched = getattr(self, "_zero3_schedule", None)
        if sched is None or steps <= 0:
            return
        from ..observability import get_registry
        reg = get_registry()
        n = steps * self.gradient_accumulation_steps()
        reg.counter(
            "ds_zero3_gather_bytes_total",
            "Scheduled ZeRO-3 param all-gather wire bytes (post-quantization)"
        ).inc(float(sched.gather_wire_bytes) * n)
        reg.counter(
            "ds_zero3_prefetch_hits_total",
            "ZeRO-3 gather epochs issued ahead of first use (T3 overlap)"
        ).inc(float(sched.prefetch_count) * n)

    def fused_train_step(self, *args, **kwargs):
        """One-program fwd+bwd+step (gas=1 only). Same semantics as
        forward();backward();step() with one dispatch and no grad buffer."""
        assert self._train_step_fused is not None, \
            "fused_train_step requires gradient_accumulation_steps == 1"
        self.tput_timer.start()
        args, kwargs = self._apply_data_efficiency(args, kwargs)
        kwargs, static_kv = _split_static_kwargs(kwargs)
        args = jax.device_put(args, self.zero_plan.batch_sharding(args))
        kwargs = jax.device_put(kwargs, self.zero_plan.batch_sharding(kwargs))
        step_fn = self._train_step_fused
        if self._wire_step is not None and self.global_steps >= self._wire_freeze_step:
            # post-warmup: packed 1-bit momentum exchange replaces the fp32
            # grad reduce (the reference's freeze_step phase switch)
            step_fn = self._wire_step
        self._flops_profile_pre(step_fn, (self.params, self.opt_state,
                                          self.scale_state, args, kwargs,
                                          static_kv))
        (loss, self.params, self.opt_state, self.scale_state, overflow,
         gnorm) = step_fn(self.params, self.opt_state, self.scale_state,
                          args, kwargs, static_kv)
        self._last_grad_norm = gnorm
        self.losses = loss
        self.micro_steps += 1
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self.tput_timer.stop(global_step=True)
        self._obs_step_mark(1)
        if self._async_window is not None:
            # zero host syncs this step: loss/overflow stay device scalars
            # until the window drains (ONE batched fetch per sync_interval)
            self._push_async_step(loss, overflow)
        else:
            if self._use_loss_scaling and bool(overflow):
                self.skipped_steps += 1
            else:
                self._advance_schedule()
            if self.monitor is not None:
                self.monitor.write_events([("Train/Samples/train_loss", float(loss),
                                            self.global_samples)])
            self._publish_registry_events()
        self._flops_profile_post()
        self._resilience_step_boundary(loss=loss, overflow=overflow)
        return loss

    def eval_batch(self, *args, **kwargs):
        """Forward-only compiled path for evaluation.

        Plain Python int/bool/str kwargs are STATIC jit arguments (flax-style
        ``deterministic`` flags, LTD keep-counts): each distinct value compiles
        once. Pass per-step varying numbers as arrays, not Python scalars.
        """
        kwargs, static_kv = _split_static_kwargs(kwargs)
        return self._fwd_only(self.params, args, kwargs, static_kv)

    def fused_train_steps(self, *args, **kwargs):
        """K optimizer steps in ONE compiled program (one dispatch).

        Every array argument carries a leading step axis ``[K, ...]``; step
        ``i`` consumes slice ``i``. Semantics are identical to calling
        ``fused_train_step`` K times (losses returned per step); requires
        gradient_accumulation_steps == 1. The win is dispatch amortization:
        host/relay round-trip cost is paid once per K steps instead of per
        step — pure upside on remote-dispatch links."""
        assert self._train_steps_fused is not None, \
            ("fused_train_steps requires gradient_accumulation_steps == 1, "
             "no optimizer offload (full or Twin-Flow partial), and a "
             "device apply program")
        if self._wire_step is not None:
            # the 1-bit wire program swaps in per-step after freeze_step;
            # a K-step scan would silently run uncompressed past the switch
            raise RuntimeError(
                "fused_train_steps does not compose with the 1-bit wire "
                "program (onebit* + comm_backend_name) — use fused_train_step")
        if (self.curriculum_scheduler_legacy is not None
                or self.random_ltd_scheduler is not None):
            # data-efficiency hooks transform each batch per step (seqlen
            # truncation changes shapes) — incompatible with one stacked
            # uniform-shape dispatch
            raise RuntimeError(
                "fused_train_steps does not compose with curriculum/"
                "random-LTD batch routing — use fused_train_step")
        kwargs, static_kv = _split_static_kwargs(kwargs)
        K = jax.tree_util.tree_leaves(args + tuple(kwargs.values()))[0].shape[0]
        args = jax.device_put(args, self.zero_plan.batch_sharding(args, stacked=True))
        kwargs = jax.device_put(kwargs,
                                self.zero_plan.batch_sharding(kwargs, stacked=True))
        self.tput_timer.start()
        self._flops_profile_pre(self._train_steps_fused,
                                (self.params, self.opt_state, self.scale_state,
                                 args, kwargs, static_kv), steps=K)
        (losses, self.params, self.opt_state, self.scale_state, overflows,
         gnorms) = self._train_steps_fused(self.params, self.opt_state,
                                           self.scale_state, args, kwargs,
                                           static_kv)
        self._last_grad_norm = gnorms[-1]
        self.losses = losses[-1]
        self.micro_steps += K
        self.global_steps += K
        self.global_samples += K * self.train_batch_size()
        # one dispatch = K real optimizer steps: the throughput timer and
        # the monitor both see K events, not one
        self.tput_timer.stop(global_step=True, steps=K)
        self._obs_step_mark(K)
        if self._async_window is not None:
            # push the whole K-step dispatch as ONE vector entry: the loss
            # vector and per-step overflow mask drain together at the window
            self._push_async_step(losses, overflows, steps=K)
        else:
            n_overflow = int(jnp.sum(overflows)) if self._use_loss_scaling else 0
            self.skipped_steps += n_overflow
            for _ in range(K - n_overflow):
                self._advance_schedule()
            if self.monitor is not None:
                base = self.global_samples - (K - 1) * self.train_batch_size()
                self.monitor.write_events(
                    [("Train/Samples/train_loss", float(l),
                      base + i * self.train_batch_size())
                     for i, l in enumerate(np.asarray(losses))])
            self._publish_registry_events(
                window_start=self.global_steps - K, window_len=K)
        self._flops_profile_post()
        self._resilience_step_boundary(losses_vec=losses, overflows_vec=overflows)
        return losses

    def module_forward(self, *args, **kwargs):
        kwargs, static_kv = _split_static_kwargs(kwargs)
        return self._fwd_only(self.params, args, kwargs, static_kv)

    # ------------------------------------------------------------------
    # info API (reference engine.py assorted getters)
    # ------------------------------------------------------------------

    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def set_train_batch_size(self, train_batch_size):
        """Adjust the GLOBAL batch by changing gradient-accumulation steps;
        the micro batch is untouched (reference engine.py:455). The compiled
        programs closed over the old gas (loss /gas scaling and the
        gas==1-vs-scan fused-path choice are baked in at build time), so they
        are rebuilt here — shape retracing alone would keep stale closures."""
        denom = self.train_micro_batch_size_per_gpu() * self.dp_world_size
        if train_batch_size <= 0 or train_batch_size % denom != 0:
            raise ValueError(
                f"train_batch_size={train_batch_size} must be a positive "
                f"multiple of micro_batch*dp={denom}")
        new_gas = train_batch_size // denom
        gas_changed = new_gas != self.gradient_accumulation_steps()
        self._config.train_batch_size = train_batch_size
        self._config.gradient_accumulation_steps = new_gas
        if gas_changed:  # gas is the only value baked into the closures
            self._build_compiled_fns()
            self._watch_compiled_fns()

    def set_train_micro_batch_size(self, micro_batch_size):
        """Adjust the micro batch, keeping gradient-accumulation steps
        (reference engine.py:473); the global batch follows."""
        if micro_batch_size <= 0:
            raise ValueError(f"micro_batch_size must be positive, got "
                             f"{micro_batch_size}")
        gas = self.gradient_accumulation_steps()
        self._config.train_micro_batch_size_per_gpu = micro_batch_size
        self._config.train_batch_size = micro_batch_size * gas * self.dp_world_size

    def get_lr(self):
        sched = self.lr_scheduler
        if sched is not None and hasattr(sched, "get_last_lr"):
            if getattr(sched, "_last_lr", None) is not None:
                # stepped (ours and torch-style both set _last_lr): any
                # exception from here is a real bug — let it surface
                return sched.get_last_lr()
            try:  # pre-step only: reference-style schedulers assert here
                return sched.get_last_lr()
            except AssertionError:
                return [self._base_lr]
        return [self._base_lr]

    def set_lr(self, lr):
        """Reference ``engine.py set_lr``: override the base learning rate.
        With a scheduler attached, the scheduler keeps driving subsequent
        steps — override its base instead (lr_schedules expose params)."""
        self._base_lr = float(lr)
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "set_base_lr"):
            self.lr_scheduler.set_base_lr(float(lr))

    def get_mom(self):
        """Reference ``engine.py get_mom``: current momentum/betas."""
        op = dict(self._config.optimizer_params or {})
        return [tuple(op.get("betas", (0.9, 0.999)))]

    def empty_partition_cache(self):
        """Reference ZeRO-3 ``empty_partition_cache``: drop gathered full
        params. Under pjit there is no host-visible gather cache — XLA frees
        gathered buffers when the step program ends — so this is a documented
        no-op kept for API portability."""
        return None

    def destroy(self):
        """Reference ``engine.destroy``: release engine state references so
        device memory can be reclaimed between engines in one process."""
        self._drain_async_window()  # settle deferred host accounting first
        self._remove_preempt_handlers()
        for attr in ("params", "opt_state", "scale_state", "_pending"):
            setattr(self, attr, None)
        self._fwd_bwd = self._fwd_only = self._apply_step = None
        self._train_step_fused = self._train_batch_fused = None
        self._train_steps_fused = None

    def get_global_grad_norm(self):
        return None if self._last_grad_norm is None else float(self._last_grad_norm)

    @property
    def cur_scale(self):
        return float(self.scale_state.cur_scale)

    def loss_scale(self):
        return self.cur_scale

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def get_sequence_parallel_group(self):
        return "seq"

    def random_ltd_enabled(self):
        return self.random_ltd_scheduler is not None

    def curriculum_enabled_legacy(self):
        return self.curriculum_scheduler_legacy is not None

    def curriculum_params_legacy(self):
        return self._config.curriculum_params_legacy

    # ------------------------------------------------------------------
    # checkpoint (reference engine.py:3109 save / :2763 load)
    # ------------------------------------------------------------------

    def _state_dict(self):
        # Under the scheduled ZeRO-3 store, "params"/"grad_acc"/"opt_state"
        # are the store pytrees: orbax writes each sharded bucket from its
        # owning chips — a per-shard save with NO full gather (the reference
        # stage-3 default; consolidation stays the explicit
        # stage3_gather_16bit_weights_on_model_save / save_16bit_model path).
        sd = {
            "params": self.params,
            "grad_acc": self.grad_acc,
            "scale_state": tuple(self.scale_state),
        }
        if self.opt_state is not None:
            sd["opt_state"] = self.opt_state
        return sd

    def full_params(self):
        """Full leaf-tree fp32 master params. Under the scheduled ZeRO-3
        store this is the one deliberate whole-model gather (store buckets
        sliced back into leaves; GSPMD gathers each bucket) — used by the
        explicit consolidation paths, and accounted to the
        ``param_gather_stall`` goodput category."""
        if getattr(self, "_zero3_store", None) is None:
            return self.params
        from .zero3_schedule import materialize_params
        meta = self._zero3_store
        with self._obs_span("param_gather_stall"):
            return jax.jit(lambda s: materialize_params(s, meta))(self.params)

    def _checkpoint_tag_validation(self, tag) -> None:
        """All processes must agree on the tag before anyone writes
        (reference engine.py:3092 _checkpoint_tag_validation): a diverged
        tag fragments one logical checkpoint across directories."""
        from ..config.feature_configs import ValidationMode
        mode = self._config.checkpoint_config.tag_validation
        if jax.process_count() == 1 or mode == ValidationMode.IGNORE:
            return
        import zlib
        from jax.experimental import multihost_utils
        h = np.asarray([zlib.crc32(str(tag).encode())], np.int64)
        all_h = np.asarray(multihost_utils.process_allgather(h)).ravel()
        if not (all_h == all_h[0]).all():
            msg = (f"checkpoint tag '{tag}' is not consistent across "
                   "processes — a mixed-tag save fragments the checkpoint")
            if mode == ValidationMode.FAIL:
                raise ValueError(msg)
            logger.warning(msg)

    def _host_state(self, client_state):
        sd = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "ds_config_batch": [self.train_batch_size(),
                                self.train_micro_batch_size_per_gpu(),
                                self.gradient_accumulation_steps()],
            "client_state": client_state or {},
        }
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "state_dict"):
            sd["lr_scheduler"] = self.lr_scheduler.state_dict()
        if self._host_optimizer is not None:
            sd["host_optimizer"] = self._host_optimizer.state_dict()
        # data-efficiency schedulers (reference engine.py:3300 saves
        # random_ltd + sampler/curriculum state in the checkpoint)
        if self.random_ltd_scheduler is not None:
            sd["random_ltd"] = self.random_ltd_scheduler.state_dict()
        if self.curriculum_scheduler_legacy is not None:
            sd["curriculum_state"] = dict(self.curriculum_scheduler_legacy.get_state())
        sampler = getattr(self.training_dataloader, "sampler", None) \
            if self.training_dataloader is not None else None
        if sampler is not None and hasattr(sampler, "state_dict"):
            sd["data_sampler"] = sampler.state_dict()
        if getattr(self, "_zero3_store", None) is not None:
            # enough to rebuild the exact bucket layout at load time (the
            # planner is deterministic given these + the leaf structs), so a
            # stage-2 engine can reshard a stage-3 checkpoint and vice versa
            m = self._zero3_store
            sd["zero3_store"] = {
                "bucket_size_mb": float(m.bucket_size_mb),
                "pad_multiple": int(m.pad_multiple),
                "persistent_idx": [int(i) for i in m.p_idx],
                "n_leaves": int(m.n_leaves),
            }
        return sd

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        exclude_frozen_parameters=False):
        # settle the async window first: deferred skipped-step / scheduler
        # accounting must land in the host state the checkpoint captures
        self._drain_async_window()
        with self._obs_span("checkpoint_save"):
            return self._save_checkpoint(save_dir, tag=tag,
                                         client_state=client_state,
                                         save_latest=save_latest)

    def _save_checkpoint(self, save_dir, tag=None, client_state=None,
                         save_latest=True):
        tag = tag or f"global_step{self.global_steps}"
        self._checkpoint_tag_validation(tag)
        self.checkpoint_engine.create(tag)
        path = os.path.join(save_dir, str(tag))
        self.checkpoint_engine.save(self._state_dict(), path,
                                    host_state=self._host_state(client_state))
        if self._config.zero_config.gather_16bit_weights_on_model_save:
            # reference stage3_gather_16bit_weights_on_model_save
            # (engine.py:3538): every checkpoint also carries consolidated
            # 16-bit weights a serving stack can load without the topology
            self.save_16bit_model(path)
        # commit BEFORE advancing `latest`: commit is the durability barrier
        # (async write settled, host state flushed, manifest + marker
        # sealed) — the old order left `latest` pointing at an uncommitted,
        # possibly torn checkpoint if the process died in between
        committed = self.checkpoint_engine.commit(tag) is not False
        if not committed:
            logger.error(f"checkpoint {tag} failed to commit; `latest` still "
                         f"points at the previous checkpoint")
            return False
        self._last_good_tag = str(tag)
        if jax.process_index() == 0:
            if save_latest:
                write_latest_tag(save_dir, tag)
            rc = getattr(self, "_resilience", None)
            if rc is not None and rc.enabled and rc.keep_last_n:
                prune_checkpoints(save_dir, rc.keep_last_n,
                                  protect=(str(tag), ))
        return True

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.npz",
                         exclude_frozen_parameters=False):
        """Consolidated 16-bit weight export (reference engine.py:3538
        _zero3_consolidated_16bit_state_dict + save_16bit_model): gathers
        the full (unsharded) bf16 weights and writes one flat archive a
        serving stack can load without the training topology."""
        from ..checkpoint.universal import _flatten
        os.makedirs(save_dir, exist_ok=True)
        # npz can't hold ml_dtypes.bfloat16 — store the bf16 bit pattern as
        # uint16 with a dtype sidecar key (fp16 stores natively)
        bf16 = self.compute_dtype == jnp.bfloat16
        sd = {}
        for k, v in _flatten(jax.tree_util.tree_map(np.asarray,
                                                    self.full_params())).items():
            if bf16:
                import ml_dtypes
                sd[k] = np.asarray(v).astype(ml_dtypes.bfloat16).view(np.uint16)
            else:
                sd[k] = np.asarray(v).astype(np.float16)
        sd["__dtype__"] = np.asarray("bfloat16" if bf16 else "float16")
        path = os.path.join(save_dir, save_filename)
        np.savez(path, **sd)
        log_dist(f"saved 16-bit model to {path} ({len(sd)} tensors)", ranks=[0])
        return True

    def load_universal_checkpoint(self, universal_dir):
        """Resume from a universal checkpoint at ANY parallelism (reference
        bf16_optimizer.py:519 load_hp_checkpoint_state / universal_checkpoint
        config flag): fp32 fragments are re-laid-out onto the live mesh's
        shardings regardless of what topology wrote them."""
        from ..checkpoint.universal import load_universal_into
        if getattr(self, "_zero3_store", None) is not None:
            raise NotImplementedError(
                "universal-checkpoint load into the scheduled ZeRO-3 param "
                "store is not supported yet — regular checkpoints reshard "
                "automatically on load_checkpoint (stage 2<->3); to consume "
                "a universal checkpoint, load it at zero stage <= 2 and "
                "save a regular checkpoint")
        params_host = jax.tree_util.tree_map(lambda x: np.zeros(x.shape, jnp.float32),
                                             jax.eval_shape(lambda p: p, self.params))
        params, opt_state, meta = load_universal_into(universal_dir, params_host,
                                                      self.opt_state)
        self.params = jax.device_put(
            jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32), params),
            self.param_shardings)
        if opt_state is not None:
            self.opt_state = jax.device_put(opt_state, self.opt_state_shardings)
        self.global_steps = meta.get("step", 0)
        log_dist(f"loaded universal checkpoint {universal_dir} at step {self.global_steps}",
                 ranks=[0])
        return universal_dir, {}

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False, custom_load_fn=None):
        # goodput: a load inside a rollback nests under "anomaly_rollback"
        with self._obs_span("checkpoint_load"):
            return self._load_checkpoint(
                load_dir, tag=tag,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_optimizer_states=load_optimizer_states,
                load_module_only=load_module_only)

    def _load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                         load_lr_scheduler_states=True,
                         load_module_only=False):
        if tag is None:
            # `latest` is authoritative while it names a sealed, verified
            # checkpoint. After a crash it may be missing, stale, or name a
            # torn/corrupt dir — then fall back through older tags until one
            # passes manifest verification (provably-bad dirs quarantined).
            lt = read_latest_tag(load_dir)
            if lt is not None and verify_checkpoint(
                    os.path.join(load_dir, str(lt)), require_manifest=True)[0]:
                tag = lt
            if tag is None:
                tag = find_latest_valid_checkpoint(load_dir)
            if tag is None and lt is not None and verify_checkpoint(
                    os.path.join(load_dir, str(lt)), require_manifest=False)[0]:
                # pre-manifest (legacy) checkpoint: the pointer is the only
                # trust anchor available — honor it
                tag = lt
            if tag is None:
                logger.warning(f"Unable to find a valid checkpoint in "
                               f"{load_dir}, if trying to load a specific "
                               "checkpoint please pass tag")
                return None, {}
        path = os.path.join(load_dir, str(tag))

        saved_store = self._peek_zero3_store_meta(path)
        if (saved_store is not None) != (getattr(self, "_zero3_store", None)
                                         is not None):
            # the checkpoint's arrays are in the OTHER param format
            # (bucketed ZeRO-3 store vs leaf tree): reshard on load
            restored, host_state = self._reshard_load(path, saved_store)
        else:
            # abstract target: restore straight into the live shardings
            target = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                if hasattr(x, "sharding") else x, self._state_dict())
            restored, host_state = self.checkpoint_engine.load(path, target=target)
        self.params = restored["params"]
        if load_optimizer_states and not load_module_only:
            if "opt_state" in restored:
                self.opt_state = restored["opt_state"]
            self.grad_acc = restored["grad_acc"]
            from .loss_scaler import LossScaleState
            self.scale_state = LossScaleState(*restored["scale_state"])
            if self._host_optimizer is not None and host_state \
                    and "host_optimizer" in host_state:
                self._host_optimizer.load_state_dict(host_state["host_optimizer"])
        client_state = {}
        if host_state:
            self.global_steps = host_state.get("global_steps", 0)
            self.global_samples = host_state.get("global_samples", 0)
            self.micro_steps = host_state.get("micro_steps", 0)
            self.skipped_steps = host_state.get("skipped_steps", 0)
            client_state = host_state.get("client_state", {})
            if (load_lr_scheduler_states and self.lr_scheduler is not None
                    and "lr_scheduler" in host_state):
                self.lr_scheduler.load_state_dict(host_state["lr_scheduler"])
            if self.random_ltd_scheduler is not None and "random_ltd" in host_state:
                self.random_ltd_scheduler.load_state_dict(host_state["random_ltd"])
            if (self.curriculum_scheduler_legacy is not None
                    and "curriculum_state" in host_state):
                self.curriculum_scheduler_legacy.set_state(host_state["curriculum_state"])
            sampler = getattr(self.training_dataloader, "sampler", None) \
                if self.training_dataloader is not None else None
            if sampler is not None and "data_sampler" in host_state:
                # resume consumed_samples + curriculum difficulty: training
                # continues on the right difficulty band, no replayed data
                sampler.load_state_dict(host_state["data_sampler"])
        self._last_good_tag = str(tag)
        return path, client_state

    def _peek_zero3_store_meta(self, path):
        """Read the checkpoint's host-state sidecar (tiny pickle, no array
        data) to learn whether its arrays were saved in ZeRO-3 store form;
        returns the saved store descriptor or None."""
        import pickle
        from ..checkpoint.engine import OrbaxCheckpointEngine
        f = os.path.join(path, OrbaxCheckpointEngine.HOST_STATE_FILE)
        if not os.path.exists(f):
            return None
        try:
            with open(f, "rb") as fh:
                hs = pickle.load(fh)
        except Exception as e:  # legacy/foreign sidecar: same-format load
            logger.warning(f"could not peek host state at {f}: {e}")
            return None
        return (hs or {}).get("zero3_store")

    def _reshard_load(self, path, saved_store):
        """Stage 2<->3 reshard-on-load: restore into an abstract target
        shaped like the SAVE-time format, then convert on device into the
        live format. Both directions are exact (pure slice/concat of fp32
        masters and moments), so a 2->3->2 round trip is bitwise."""
        from .zero3_schedule import (build_store_meta, map_store_subtrees,
                                     materialize_params, store_from_tree)
        repl = self.mesh_ctx.replicated()

        def _repl_struct(t):
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=repl), t)

        acc_dtype = self.grad_accum_dtype
        scale_target = _repl_struct(tuple(self.scale_state))
        if saved_store is not None:
            # checkpoint holds the bucketed store; live engine wants a tree
            fp32_tree = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, jnp.float32),
                self.params)
            meta = build_store_meta(fp32_tree, saved_store["persistent_idx"],
                                    saved_store["bucket_size_mb"],
                                    saved_store["pad_multiple"])
            if meta.n_leaves != int(saved_store.get("n_leaves",
                                                    meta.n_leaves)):
                raise ValueError(
                    f"checkpoint ZeRO-3 store covers "
                    f"{saved_store['n_leaves']} param leaves but the live "
                    f"model has {meta.n_leaves}")

            def _store_struct(dtype):
                return {"buckets": [jax.ShapeDtypeStruct((b.padded_size, ),
                                                         dtype, sharding=repl)
                                    for b in meta.layout.buckets],
                        "persistent": [jax.ShapeDtypeStruct(
                            meta.leaf_structs[i].shape, dtype, sharding=repl)
                            for i in meta.p_idx]}

            target = {"params": _store_struct(jnp.float32),
                      "grad_acc": _store_struct(acc_dtype),
                      "scale_state": scale_target}
            if self.opt_state is not None:
                target["opt_state"] = _repl_struct(jax.eval_shape(
                    self.base_tx.init, _store_struct(jnp.float32)))
            restored, host_state = self.checkpoint_engine.load(path,
                                                               target=target)
            out = {"params": jax.jit(
                       lambda s: materialize_params(s, meta),
                       out_shardings=self.param_shardings)(restored["params"]),
                   "grad_acc": jax.jit(
                       lambda s: materialize_params(s, meta),
                       out_shardings=self.grad_shardings)(restored["grad_acc"]),
                   "scale_state": restored["scale_state"]}
            if "opt_state" in restored:
                store_def = jax.tree_util.tree_structure(
                    _store_struct(jnp.float32))
                out["opt_state"] = jax.jit(
                    lambda o: map_store_subtrees(
                        o, store_def, lambda s: materialize_params(s, meta)),
                    out_shardings=self.opt_state_shardings)(
                        restored["opt_state"])
            log_dist(f"resharded ZeRO-3 store checkpoint {path} into the "
                     f"live leaf-tree layout (stage 3 -> "
                     f"{self.zero_plan.stage})", ranks=[0])
            return out, host_state
        # checkpoint holds a leaf tree; live engine runs the ZeRO-3 store
        meta = self._zero3_store
        leaves_f32 = [jax.ShapeDtypeStruct(s.shape, jnp.float32,
                                           sharding=repl)
                      for s in meta.leaf_structs]
        fp32_tree = jax.tree_util.tree_unflatten(meta.treedef, leaves_f32)
        acc_tree = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, acc_dtype, sharding=repl),
            fp32_tree)
        target = {"params": fp32_tree, "grad_acc": acc_tree,
                  "scale_state": scale_target}
        if self.opt_state is not None:
            target["opt_state"] = _repl_struct(jax.eval_shape(
                self.base_tx.init, fp32_tree))
        restored, host_state = self.checkpoint_engine.load(path,
                                                           target=target)
        out = {"params": jax.jit(
                   lambda t: store_from_tree(t, meta),
                   out_shardings=self.param_shardings)(restored["params"]),
               "grad_acc": jax.jit(
                   lambda t: store_from_tree(t, meta),
                   out_shardings=self.grad_shardings)(restored["grad_acc"]),
               "scale_state": restored["scale_state"]}
        if "opt_state" in restored:
            out["opt_state"] = jax.jit(
                lambda o: map_store_subtrees(
                    o, meta.treedef, lambda t: store_from_tree(t, meta)),
                out_shardings=self.opt_state_shardings)(restored["opt_state"])
        log_dist(f"resharded leaf-tree checkpoint {path} into the live "
                 f"ZeRO-3 bucket store", ranks=[0])
        return out, host_state
