"""Hybrid engine — train + generate in one engine (RLHF).

Reference: ``runtime/hybrid_engine.py:32 DeepSpeedHybridEngine``: during
RLHF, actor training interleaves with rollout generation; the reference
flips each decoder layer into its fused inference container for
``generate()`` and back for training, sharing weights in place.

TPU design: the training engine owns fp32 master params; ``generate()``
serves rollouts through the v2 ragged paged-KV engine over a *view* of
those same params (cast once per refresh — the analog of the reference's
weight-sharing container flip, without module surgery: both paths are pure
functions over the same tree). After each optimizer step the inference view
is marked stale and recast lazily on the next generate.
"""

from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logging import logger
from .engine import DeepSpeedTpuEngine


class DeepSpeedHybridEngine(DeepSpeedTpuEngine):

    def __init__(self, *args, llama_config=None, generate_config=None, **kwargs):
        super().__init__(*args, **kwargs)
        hec = self._config.hybrid_engine_config or {}
        self._he_dtype = jnp.bfloat16 if hec.get("fp16", True) else jnp.float32
        self._llama_config = llama_config
        self._gen_engine = None
        self._gen_params_version = -1
        self._inference_mode = False
        self._kv_block_size = hec.get("kv_block_size", 64)
        self._num_kv_blocks = hec.get("num_kv_blocks", 512)
        self._max_context = hec.get("max_out_tokens", 2048)
        # RLHF rollouts re-prefill the same prompts many times per weight
        # version (N samples per prompt): prefix caching pays the prompt
        # prefill once. Cache entries are invalidated at every weight swap
        # (stale-KV guard in _refresh_generation_engine).
        self._he_prefix_caching = hec.get("prefix_caching", False)

    # ---- mode flips (reference eval()/train() container swaps) ----

    def eval(self):
        self._inference_mode = True
        return self

    def train(self, mode: bool = True):
        self._inference_mode = not mode
        return self

    def step(self, *a, **kw):
        out = super().step(*a, **kw)
        # params changed → inference view is stale (reference re-shards
        # containers on the fly; we just recast lazily)
        self._gen_params_version = -1
        return out

    # ---- generation (reference generate() :238) ----

    def _refresh_generation_engine(self):
        if self._llama_config is None:
            raise RuntimeError("hybrid generate() needs llama_config (the flax "
                               "LlamaConfig of the wrapped model)")
        from ..inference.v2 import (InferenceEngineV2, RaggedInferenceEngineConfig)
        from ..inference.v2.config_v2 import DSStateManagerConfig
        from ..inference.v2.model import RaggedLlamaModel

        if self._gen_params_version == self.global_steps and self._gen_engine is not None:
            return
        params = self.params
        # under native TP training the live weights are model-sharded; the
        # serving model must run its TP dispatch (shard_map'd paged kernel,
        # head-sharded KV) or the raw kernel would see sharded operands
        tp = (self.mesh_ctx.axis_size("model")
              if getattr(self, "_tp_training", False) else 1)
        model = RaggedLlamaModel(self._llama_config, params, dtype=self._he_dtype,
                                 kv_block_size=self._kv_block_size, tp_size=tp)
        if self._gen_engine is None:
            cfg = RaggedInferenceEngineConfig(
                state_manager=DSStateManagerConfig(max_context=self._max_context),
                num_kv_blocks=self._num_kv_blocks,
                enable_prefix_caching=self._he_prefix_caching)
            self._gen_engine = InferenceEngineV2(model, cfg)
        else:
            # keep the KV cache + state manager; swap the weights (this is
            # the in-place weight sharing the reference gets from containers)
            # — but cached prefixes hold KV computed under the OLD weights:
            # adopting them after a step would serve stale activations
            self._gen_engine._state_manager.reset_prefix_cache()
            model.set_state_manager(self._gen_engine._state_manager)
            old = self._gen_engine._model
            if (old.attn_backend == model.attn_backend
                    and old.tp_size == model.tp_size):
                # the compiled serving fns take params as an ARGUMENT and
                # close only over refresh-invariants (config, block size,
                # backend, tp, mesh) — carrying them over skips a full
                # retrace+XLA recompile per optimizer step (under TP, a
                # multi-device GSPMD compile)
                model._fwd_cache = old._fwd_cache
            self._gen_engine._model = model
        self._gen_params_version = self.global_steps

    def generate(self, input_ids, max_new_tokens: int = 16, do_sample: bool = False,
                 temperature: float = 1.0, seed: int = 0, eos_token_id: Optional[int] = None):
        """Batched rollout generation with paged KV (greedy or sampled).
        input_ids: [batch, prompt_len] (list/array; left-unpadded)."""
        self._refresh_generation_engine()
        eng = self._gen_engine
        prompts = [np.asarray(row, dtype=np.int32).reshape(-1) for row in input_ids]
        uids = list(range(len(prompts)))
        key = jax.random.PRNGKey(seed)

        out = [list(p) for p in prompts]
        done = [False] * len(prompts)
        logits = eng.put(uids, prompts)
        for step in range(max_new_tokens):
            lg = np.asarray(logits)[:len(prompts)]
            if do_sample:
                key, sub = jax.random.split(key)
                nxt = np.asarray(jax.random.categorical(sub, jnp.asarray(lg) / temperature))
            else:
                nxt = lg.argmax(-1)
            for i in range(len(prompts)):
                if not done[i]:
                    out[i].append(int(nxt[i]))
                    if eos_token_id is not None and int(nxt[i]) == eos_token_id:
                        done[i] = True
            if all(done) or step == max_new_tokens - 1:
                break
            live = [i for i in range(len(prompts)) if not done[i]]
            logits_live = eng.put([uids[i] for i in live], [[out[i][-1]] for i in live])
            # scatter live rows back into a full-width logits view
            lg_full = np.zeros((len(prompts), np.asarray(logits_live).shape[-1]),
                               dtype=np.float32)
            for row, i in enumerate(live):
                lg_full[i] = np.asarray(logits_live)[row]
            logits = lg_full
        for uid in uids:
            eng.flush(uid)
        return out
