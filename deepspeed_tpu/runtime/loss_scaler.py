"""Loss scaling for fp16 training.

Rebuild of reference ``deepspeed/runtime/fp16/loss_scaler.py`` (LossScaler :67,
DynamicLossScaler :91) as a jit-compatible pytree state + pure update rule, so
the overflow check / scale adjustment lives inside the compiled train step
(the reference does this host-side between CUDA kernels; on TPU a host round
trip per step would stall the pipeline).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    """Dynamic loss-scale state; all fields device scalars."""
    cur_scale: jnp.ndarray  # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iter: jnp.ndarray  # i32 scalar


def make_static_state(scale: float) -> LossScaleState:
    return LossScaleState(cur_scale=jnp.float32(scale),
                          cur_hysteresis=jnp.int32(1),
                          last_overflow_iter=jnp.int32(-1),
                          iter=jnp.int32(0))


def make_dynamic_state(init_scale_power: int = 16, delayed_shift: int = 2) -> LossScaleState:
    return LossScaleState(cur_scale=jnp.float32(2.0**init_scale_power),
                          cur_hysteresis=jnp.int32(delayed_shift),
                          last_overflow_iter=jnp.int32(-1),
                          iter=jnp.int32(0))


def has_overflow(grads) -> jnp.ndarray:
    """True if any grad entry is non-finite (reference CheckOverflow)."""
    leaves = jax.tree_util.tree_leaves(grads)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    out = flags[0] if flags else jnp.bool_(False)
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def update_scale(state: LossScaleState,
                 overflow: jnp.ndarray,
                 scale_factor: float = 2.0,
                 scale_window: int = 1000,
                 min_scale: float = 1.0,
                 max_scale: float = 2.0**32,
                 delayed_shift: int = 2,
                 consecutive_hysteresis: bool = False) -> LossScaleState:
    """Pure DynamicLossScaler.update_scale (reference loss_scaler.py:137)."""
    # overflow path: burn hysteresis first, then halve the scale
    use_hyst = jnp.logical_and(overflow, state.cur_hysteresis > 1)
    scale_on_overflow = jnp.where(use_hyst, state.cur_scale,
                                  jnp.maximum(state.cur_scale / scale_factor, min_scale))
    hyst_on_overflow = jnp.where(use_hyst, state.cur_hysteresis - 1, state.cur_hysteresis)

    # growth path: double every scale_window clean iters
    # grow when (cur_iter - last_overflow_iter) % window == 0, cur_iter
    # 0-based and incremented after the check (reference loss_scaler.py:199):
    # with last=-1 the first growth lands on iter 999 for window=1000
    clean_run = (state.iter - state.last_overflow_iter) % scale_window == 0
    scale_on_ok = jnp.where(clean_run, jnp.minimum(state.cur_scale * scale_factor, max_scale),
                            state.cur_scale)
    hyst_on_ok = (jnp.int32(delayed_shift) if consecutive_hysteresis else state.cur_hysteresis)

    return LossScaleState(
        cur_scale=jnp.where(overflow, scale_on_overflow, scale_on_ok),
        cur_hysteresis=jnp.where(overflow, hyst_on_overflow, hyst_on_ok),
        last_overflow_iter=jnp.where(overflow, state.iter, state.last_overflow_iter),
        iter=state.iter + 1,
    )


class LossScalerConfig(NamedTuple):
    """Static knobs resolved from FP16Config."""
    dynamic: bool
    init_scale_power: int
    scale_window: int
    hysteresis: int
    consecutive_hysteresis: bool
    min_scale: float
    static_scale: float

    @classmethod
    def from_fp16_config(cls, c):
        return cls(dynamic=(c.loss_scale == 0),
                   init_scale_power=c.initial_scale_power,
                   scale_window=c.loss_scale_window,
                   hysteresis=c.hysteresis,
                   consecutive_hysteresis=c.consecutive_hysteresis,
                   min_scale=c.min_loss_scale,
                   static_scale=c.loss_scale if c.loss_scale else 1.0)

    def initial_state(self) -> LossScaleState:
        if self.dynamic:
            return make_dynamic_state(self.init_scale_power, self.hysteresis)
        return make_static_state(self.static_scale)

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        if not self.dynamic:
            return state._replace(iter=state.iter + 1)
        return update_scale(state,
                            overflow,
                            scale_window=self.scale_window,
                            min_scale=self.min_scale,
                            delayed_shift=self.hysteresis,
                            consecutive_hysteresis=self.consecutive_hysteresis)
