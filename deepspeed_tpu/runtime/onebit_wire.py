"""1-bit Adam/LAMB compressed WIRE train program.

Reference: ``runtime/comm/nccl.py:16 compressed_allreduce`` driving
``runtime/fp16/onebit/adam.py`` — post-warmup, the DP exchange carries sign
bits + scales instead of fp32 gradients (~32x wire reduction,
docs/_tutorials/onebit-adam.md).

TPU shape: the engine's normal fused step lets GSPMD emit the fp32 gradient
psum. This module builds the POST-WARMUP alternative: a ``shard_map``
program with the data-parallel axes manual, where

  1. each worker computes LOCAL gradients (no implicit psum — the axis is
     manual),
  2. the optimizer's momentum update runs on local grads and the momentum is
     exchanged through ``comm.compressed.compressed_allreduce_tree`` — the
     arrays crossing ICI are the packed uint8 sign bits + one scale per
     worker,
  3. every worker applies the identical averaged update, keeping the
     replicated-parameter invariant (variance is frozen post-warmup, so no
     unreduced statistic can diverge).

The engine dispatches: steps < freeze_step run the standard program (exact
Adam on reduced grads — the reference's uncompressed warmup), steps >=
freeze_step run this program. The phase switch is a host-side compile-time
decision, mirroring the reference's Python branch at freeze_step.

Constraints (checked): gas=1, ZeRO stage 0 (replicated params/opt state),
pure-DP mesh (model/seq/expert/pipe axes trivial), no fp16 loss scaling,
no global gradient clipping (it would need the fp32 reduce this avoids).

Known limitation: the error-feedback buffers are per-worker by design
(reference semantics); they ride the replicated opt-state slot, so a
checkpoint captures worker 0's buffer and a restore resets the others'
residuals — bounded impact, the feedback re-accumulates within a few steps.
"""

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.compressed import compressed_allreduce_tree
from ..utils.logging import log_dist

try:
    from jax import shard_map as _shard_map

    def _smap(f, mesh, in_specs, out_specs, axes):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          axis_names=set(axes), check_vma=False)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _old

    def _smap(f, mesh, in_specs, out_specs, axes):
        auto = {"pipe", "data", "fsdp", "seq", "expert", "model"} - set(axes)
        return _old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False, auto=frozenset(auto))


def wire_supported(engine) -> bool:
    cfg = engine._config
    ctx = engine.mesh_ctx
    dp = sum(ctx.axis_size(a) > 1 for a in ("data", "fsdp"))
    return (cfg.gradient_accumulation_steps == 1
            and cfg.zero_config.stage == 0
            and not cfg.fp16_enabled
            and not cfg.gradient_clipping  # global-grad clip needs the fp32 reduce
            and dp >= 1  # something to compress across
            and all(ctx.axis_size(a) == 1 for a in ("model", "seq", "expert", "pipe")))


def build_wire_step(engine, name: str):
    """Compile the post-warmup compressed-wire step for `engine`. Returns a
    callable with the engine's fused-step signature
    ``(params, opt_state, scale_state, args, kwargs, static_kv)``."""
    from .onebit import build_onebit_optimizer
    from .engine import _extract_loss

    if not wire_supported(engine):
        raise ValueError(
            "the 1-bit compressed wire program needs gas=1, ZeRO stage 0, "
            "bf16/fp32, and a pure data-parallel mesh")
    ctx = engine.mesh_ctx
    mesh = ctx.mesh
    dp_axes = tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)
    ax = dp_axes if len(dp_axes) > 1 else dp_axes[0]  # lax collective axis arg
    compute_dtype = engine.compute_dtype
    apply_fn = engine.apply_fn
    gas = 1

    exchange = partial(compressed_allreduce_tree, axis_names=ax)
    tx = build_onebit_optimizer(name, dict(engine._config.optimizer_params or {}),
                                engine._lr_fn or engine._base_lr,
                                exchange_fn=exchange)

    def local_step(params, opt_state, args, kwargs, static_kv):
        def loss_of(p):
            cp = jax.tree_util.tree_map(lambda x: x.astype(compute_dtype), p)
            out = apply_fn(cp, *args, **dict(kwargs, **dict(static_kv)))
            loss, _ = _extract_loss(out)
            return loss.astype(jnp.float32) / gas

        loss, grads = jax.value_and_grad(loss_of)(params)  # LOCAL grads
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # diagnostic only: mean of per-worker local-grad norms (the true
        # global-grad norm would require the fp32 reduce this program avoids)
        gnorm = jax.lax.pmean(optax.global_norm(grads), ax)
        loss = jax.lax.pmean(loss, ax)
        return loss, new_params, new_opt, gnorm

    repl = NamedSharding(mesh, P())
    batch_spec = P(ax)

    def step(params, opt_state, scale_state, args, kwargs, static_kv):
        def region(params, opt_state, args, kwargs):
            return local_step(params, opt_state, args, kwargs, static_kv)

        in_specs = (P(), P(),
                    jax.tree_util.tree_map(lambda _: batch_spec, args),
                    jax.tree_util.tree_map(lambda _: batch_spec, kwargs))
        fn = _smap(region, mesh, in_specs, (P(), P(), P(), P()), dp_axes)
        loss, new_params, new_opt, gnorm = fn(params, opt_state, args, kwargs)
        # same output arity as the engine's fused step
        return (loss, new_params, new_opt, scale_state,
                jnp.bool_(False), gnorm)

    from .loss_scaler import LossScaleState
    jitted = jax.jit(step, donate_argnums=(0, 1), static_argnums=(5, ),
                     out_shardings=(None, engine.param_shardings,
                                    engine.opt_state_shardings,
                                    LossScaleState(*engine.scale_state_shardings),
                                    repl, repl))
    log_dist(f"1-bit wire program built: dp axes {dp_axes}, "
             f"optimizer {name} (packed uint8 sign exchange)", ranks=[0])
    return jitted
