"""FP8 training (TransformerEngine-composability analog).

Reference: DeepSpeed composes with TransformerEngine's fp8 autocast and
proves it across ZeRO stages (``tests/unit/runtime/half_precision/
test_fp8.py:23 TestFp8ComposabilityAcrossZero``). There is no TE on TPU;
the TPU-native form is a functional fp8 matmul with per-tensor CURRENT
scaling (TE's "current scaling" recipe — scales computed from the tensor
being cast, no history state to thread through jit) and the HYBRID format:

- forward operands in ``float8_e4m3fn`` (more mantissa),
- backward gradient operand in ``float8_e5m2`` (more range),
- accumulation always fp32 (``preferred_element_type``).

XLA lowers fp8 ``dot_general`` natively (hardware fp8 MXU paths where the
chip has them; wider-math emulation elsewhere), so the same program is
correct on every backend and fast where silicon allows. The residuals
saved for backward are the QUANTIZED operands + scales — the fp8 memory
saving applies to saved activations too, which is the actual training win
on HBM-bound steps.

Composability with ZeRO needs nothing special by construction: params stay
in the base dtype (fp32/bf16 master semantics are the engine's business),
and the fp8 cast lives inside the traced step, so stages 0-3 shard the
same pytrees they always shard. ``tests/unit/runtime/test_fp8.py`` proves
stage-identical trajectories, mirroring the reference test's shape.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2


def _quantize(t: jax.Array, dtype) -> tuple:
    """Per-tensor current scaling: q = t / scale in `dtype`, with
    scale = amax / dtype_max so the largest magnitude maps to the top of
    the representable range. Returns (q, scale_f32)."""
    fmax = jnp.float32(jnp.finfo(dtype).max)
    amax = jnp.max(jnp.abs(t)).astype(jnp.float32)
    scale = jnp.maximum(amax, 1e-12) / fmax
    q = (t.astype(jnp.float32) / scale).astype(dtype)
    return q, scale


def _dot_f32(a, b):
    return jax.lax.dot_general(a, b, (((a.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


@jax.custom_vjp
def _fp8_matmul_2d(x: jax.Array, w: jax.Array) -> jax.Array:
    qx, sx = _quantize(x, E4M3)
    qw, sw = _quantize(w, E4M3)
    return _dot_f32(qx, qw) * (sx * sw)


def _fp8_fwd(x, w):
    qx, sx = _quantize(x, E4M3)
    qw, sw = _quantize(w, E4M3)
    y = _dot_f32(qx, qw) * (sx * sw)
    # residuals are the fp8 tensors — backward re-reads 1 byte/elem; the
    # primal dtypes ride along (as 0-d tokens: a raw np.dtype is not a
    # valid residual leaf) so cotangents match bf16/fp32 primals
    return y, (qx, sx, qw, sw, jnp.zeros((), x.dtype), jnp.zeros((), w.dtype))


def _fp8_bwd(res, g):
    qx, sx, qw, sw, xtok, wtok = res
    qg, sg = _quantize(g, E5M2)
    # dx = g @ w^T ; dw = x^T @ g — both with an e5m2 grad operand and an
    # e4m3 saved operand, fp32 accumulation
    dx = _dot_f32(qg, qw.T) * (sg * sw)
    dw = _dot_f32(qx.T, qg) * (sx * sg)
    return dx.astype(xtok.dtype), dw.astype(wtok.dtype)


_fp8_matmul_2d.defvjp(_fp8_fwd, _fp8_bwd)


def fp8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """y = x @ w with e4m3 operands and fp32 accumulation; gradients flow
    through e5m2-quantized cotangents (HYBRID recipe). ``x`` may carry
    leading batch dims (contracted against 2D ``w``)."""
    if w.ndim != 2:
        raise ValueError(f"fp8_matmul expects 2D weights, got {w.shape}")
    lead = x.shape[:-1]
    y = _fp8_matmul_2d(x.reshape(-1, x.shape[-1]), w)
    return y.reshape(*lead, w.shape[-1])


class Fp8Linear(nn.Module):
    """Drop-in linear whose matmul runs in fp8 (reference analog:
    ``transformer_engine.Linear`` under ``fp8_autocast``; composability
    contract from ``test_fp8.py:23``). Params stay in ``param_dtype`` —
    ZeRO/bf16-master semantics are untouched."""
    features: int
    use_bias: bool = True
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        kinit = self.kernel_init or nn.initializers.lecun_normal()
        kernel = self.param("kernel", kinit, (d_in, self.features),
                            self.param_dtype)
        y = fp8_matmul(x, kernel)
        # keep the surrounding model's activation dtype: emitting raw fp32
        # from every fp8 layer would silently double activation memory in
        # a bf16 model — the opposite of the fp8 point
        out_dt = jnp.promote_types(x.dtype, self.param_dtype)
        y = y.astype(out_dt)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features, ), self.param_dtype)
            y = y + bias.astype(out_dt)
        return y


def quantization_error(t: jax.Array, dtype=E4M3) -> float:
    """Relative L2 error of one fp8 round-trip at the current scale —
    the observability hook the reference gets from TE's amax history."""
    q, s = _quantize(t, dtype)
    back = q.astype(jnp.float32) * s
    num = jnp.linalg.norm(t.astype(jnp.float32) - back)
    return float(num / jnp.maximum(jnp.linalg.norm(t.astype(jnp.float32)), 1e-12))
