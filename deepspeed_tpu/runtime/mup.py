"""muP (maximal update parametrization) optimizers.

Reference: ``runtime/config.py:79-81`` accepts ``optimizer.type`` =
MuAdam/MuAdamW/MuSGD and delegates the width-dependent per-parameter
learning rates to the external ``mup`` package (models annotated via
``mup.set_base_shapes``; exercised by
``tests/unit/runtime/test_mup_optimizers.py``). The TPU rebuild keeps the
same JSON surface but makes the width bookkeeping functional: the user
derives ``base_shapes`` from a BASE-width param tree once
(:func:`make_base_shapes` — a JSON-able {path: shape} dict) and passes it
in ``optimizer.params.base_shapes``; the optimizer factory scales each
leaf's update by the μTransfer rule.

Rules (Tensor Programs V / μTransfer Table 3), with a dimension counted
"infinite" when it differs from the base shape, and the trailing two axes
of an ndim≥2 kernel read as ``(fan_in, fan_out)`` (flax ``[..., in, out]``
convention; leading axes such as a scan-stacked layer dim are layout, not
width):

==========  ===========================  ==================
leaf kind   infinite dims                LR multiplier
==========  ===========================  ==================
Adam-family hidden/output (fan_in inf)   1 / fan_in_mult
Adam-family input-like, biases           1
SGD         hidden (both inf)            1
SGD         input-like / bias (out inf)  fan_out_mult
SGD         output-like (fan_in inf)     1 / fan_in_mult
==========  ===========================  ==================

At the base width every multiplier is exactly 1, so a μ-optimizer on the
base model is bit-identical to its plain counterpart — asserted in tests,
as is μTransfer's point: hidden-layer effective LR shrinks ∝ 1/width when
the model widens while input/bias LRs hold.
"""

from typing import Any, Dict, List, Tuple

import jax
import optax

from ..utils.logging import logger


def _path_str(path: Tuple) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts)


def make_base_shapes(base_params) -> Dict[str, List[int]]:
    """Record the BASE-width shapes as a JSON-able {path: [dims]} dict
    (the ``mup.make_base_shapes`` analog — run once on the narrow model)."""
    flat = jax.tree_util.tree_flatten_with_path(base_params)[0]
    return {_path_str(path): list(leaf.shape) for path, leaf in flat}


def _leaf_mult(shape: Tuple[int, ...], base: List[int], family: str,
               path: str) -> float:
    if list(shape) == list(base):
        return 1.0
    if len(shape) != len(base):
        raise ValueError(
            f"muP base shape for {path} has rank {len(base)} but the model "
            f"leaf has rank {len(shape)} — base_shapes from a different model?")
    if len(shape) == 0:
        return 1.0
    if len(shape) == 1:
        mult = shape[0] / base[0]
        # a widening vector (bias / layernorm scale) is "input-like":
        # Adam leaves it alone, SGD scales it up with width
        return mult if family == "sgd" else 1.0
    fan_in_mult = shape[-2] / base[-2]
    fan_out_mult = shape[-1] / base[-1]
    fan_in_inf = shape[-2] != base[-2]
    fan_out_inf = shape[-1] != base[-1]
    if family == "adam":
        # hidden AND output weights: lr ∝ 1/fan_in; input-like unchanged
        return 1.0 / fan_in_mult if fan_in_inf else 1.0
    # sgd
    if fan_in_inf and fan_out_inf:
        return 1.0
    if fan_out_inf:
        return fan_out_mult
    if fan_in_inf:
        return 1.0 / fan_in_mult
    return 1.0


def width_multipliers(params, base_shapes: Dict[str, Any], family: str):
    """Per-leaf LR multiplier tree for ``family`` in {"adam", "sgd"}."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    mults = []
    missing = []
    for path, leaf in flat:
        key = _path_str(path)
        if key not in base_shapes:
            missing.append(key)
            mults.append(1.0)
        else:
            mults.append(_leaf_mult(tuple(leaf.shape), base_shapes[key],
                                    family, key))
    if missing:
        raise ValueError(
            f"muP base_shapes missing {len(missing)} param paths "
            f"(e.g. {missing[:3]}) — regenerate with make_base_shapes() "
            f"on a BASE-width model with the same structure")
    return jax.tree_util.tree_unflatten(treedef, mults)


def scale_updates_by_mup(base_shapes: Dict[str, Any],
                         family: str) -> optax.GradientTransformation:
    """optax transform multiplying each leaf's update by its μP LR
    multiplier. Shapes are static under jit, so the multiplier tree is
    resolved at trace time from the updates themselves."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        mults = width_multipliers(updates, base_shapes, family)
        scaled = jax.tree_util.tree_map(lambda u, m: u * m, updates, mults)
        return scaled, state

    return optax.GradientTransformation(init, update)


def build_mu_optimizer(name: str, params: Dict[str, Any],
                       learning_rate) -> optax.GradientTransformation:
    """Factory for optimizer.type muadam/muadamw/musgd
    (reference ``runtime/config.py:79-81``)."""
    from .optimizers import ADAM_DEFAULT_BETAS  # one source for defaults

    base_shapes = params.get("base_shapes")
    if not isinstance(base_shapes, dict) or not base_shapes:
        raise ValueError(
            f"{name} needs optimizer.params.base_shapes "
            f"(make_base_shapes(base_width_params) — the mup "
            f"set_base_shapes analog)")
    betas = params.get("betas", ADAM_DEFAULT_BETAS)
    eps = float(params.get("eps", 1e-8))
    wd = float(params.get("weight_decay", 0.0))
    momentum = float(params.get("momentum", 0.0))
    nesterov = bool(params.get("nesterov", False))
    if name in ("muadam", "muadamw"):
        chain = [optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
                 scale_updates_by_mup(base_shapes, "adam")]
        if name == "muadamw" and wd:
            # decoupled wd stays UNSCALED relative to the global lr
            # (μTransfer's "independent weight decay")
            chain.append(optax.add_decayed_weights(wd))
        elif wd:
            logger.warning("muadam ignores weight_decay (use muadamw)")
        chain.append(optax.scale_by_learning_rate(learning_rate))
        return optax.chain(*chain)
    if name == "musgd":
        chain = []
        if wd:
            # L2-style (into the gradient), matching the plain sgd branch
            chain.append(optax.add_decayed_weights(wd))
        if momentum:
            chain.append(optax.trace(decay=momentum, nesterov=nesterov))
        chain.append(scale_updates_by_mup(base_shapes, "sgd"))
        chain.append(optax.scale_by_learning_rate(learning_rate))
        return optax.chain(*chain)
    raise ValueError(f"unknown mu optimizer {name}")
