"""Progressive Layer Drop.

Reference: ``runtime/progressive_layer_drop.py:10 ProgressiveLayerDrop`` —
keep-probability schedule theta(t) = (1-theta)·exp(-gamma·t) + theta; layer
i of L keeps with prob 1 - (i/L)(1-theta(t)). The schedule object is host
state; the drop itself is a functional helper usable inside jit (bernoulli
mask scaling the residual branch, identity at eval)."""

import math
from typing import Optional

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int):
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta


def layer_drop_keep_prob(theta: float, layer_idx: int, num_layers: int) -> float:
    """Per-layer keep probability (deeper layers drop more)."""
    return 1.0 - (layer_idx / max(1, num_layers)) * (1.0 - theta)


def apply_layer_drop(residual_out, x, keep_prob, rng_key, deterministic: bool = False):
    """Stochastic-depth residual: x + m/p · f(x) with m~Bern(p) (train), or
    x + f(x) (eval) — inverted scaling keeps expectation fixed."""
    if deterministic:
        return x + residual_out
    keep = jax.random.bernoulli(rng_key, keep_prob)
    scale = jnp.where(keep, 1.0 / keep_prob, 0.0).astype(residual_out.dtype)
    return x + residual_out * scale
