"""1-bit / 0/1 Adam and 1-bit LAMB optimizers.

Rebuild of reference ``runtime/fp16/onebit/{adam,zoadam,lamb}.py``: after a
full-precision warmup phase, the momentum is communicated in sign+scale form
with an error-feedback buffer (error compensation), and (for 1-bit Adam) the
variance term is frozen at its warmup value.

TPU note: the reference pairs this math with custom NCCL/MPI compressed
collectives (``runtime/comm/nccl.py compressed_allreduce``). Under SPMD/XLA
the gradient all-reduce is emitted by the compiler, so the compression here is
expressed as the *numerics* (sign+scale with error feedback applied to the
momentum update); the wire-compression analog over ICI is provided by the
quantized-collective kernels in ``ops/pallas/quant.py`` + shard_map reductions
(ZeRO++ qgZ path), which share this module's sign/scale math.
"""

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any  # momentum (exchanged compressed after warmup)
    nu: Any  # variance (frozen after warmup for 1-bit Adam)
    error: Any  # error-feedback buffer


def _sign_compress(x, error):
    """Error-compensated 1-bit compression: sign + per-tensor L1 scale.
    Returns (compressed, new_error); reference compressed_allreduce
    (runtime/comm/nccl.py:16) packs the sign bits for the wire.

    Sign convention: >= 0 maps to +1 — one bit has no zero, and the
    reference wire packs exactly this (``sign().add_(1).bool()``). The local
    path MUST match or it silently diverges from the wire program on
    exactly-zero elements (dead units)."""
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    compressed = jnp.where(corrected >= 0, scale, -scale)
    new_error = corrected - compressed
    return compressed, new_error


def scale_by_onebit_adam(b1: float = 0.9,
                         b2: float = 0.999,
                         eps: float = 1e-8,
                         freeze_step: int = 100000,
                         var_freeze: bool = True,
                         exchange_fn=None) -> optax.GradientTransformation:
    """1-bit Adam (reference onebit/adam.py:14). Before `freeze_step`: exact
    Adam. After: variance frozen, momentum sign-compressed w/ error feedback.

    ``exchange_fn(mu_tree, error_tree) -> (avg_tree, new_error_tree)`` swaps
    the local sign compression for a REAL wire exchange
    (comm/compressed.py compressed_allreduce_tree inside a shard_map region);
    used by the engine's post-warmup wire program (onebit_wire.py)."""

    def init_fn(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        return OneBitAdamState(count=jnp.zeros([], jnp.int32),
                               mu=jax.tree_util.tree_map(jnp.zeros_like, params),
                               nu=jax.tree_util.tree_map(jnp.zeros_like, params),
                               error=zeros)

    def update_fn(updates, state, params=None):
        count = state.count + 1
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        in_warmup = count <= freeze_step

        # warmup variance update; frozen afterwards
        nu_warm = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * (g * g), state.nu, updates)
        nu = jax.tree_util.tree_map(lambda w, f: jnp.where(in_warmup, w, f), nu_warm, state.nu) \
            if var_freeze else nu_warm

        # compressed momentum (post-warmup)
        if exchange_fn is not None:
            mu_comp, err_new = exchange_fn(mu, state.error)
        else:
            comp_and_err = jax.tree_util.tree_map(_sign_compress, mu, state.error)
            mu_comp = jax.tree_util.tree_map(lambda ce: ce[0], comp_and_err,
                                             is_leaf=lambda x: isinstance(x, tuple))
            err_new = jax.tree_util.tree_map(lambda ce: ce[1], comp_and_err,
                                             is_leaf=lambda x: isinstance(x, tuple))
        mu_used = jax.tree_util.tree_map(lambda w, c: jnp.where(in_warmup, w, c), mu, mu_comp)
        error = jax.tree_util.tree_map(lambda e_old, e_new: jnp.where(in_warmup, e_old, e_new),
                                       state.error, err_new)

        c = count.astype(jnp.float32)
        bc1 = 1 - b1**c
        bc2 = 1 - b2**c
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu_used, nu)
        return new_updates, OneBitAdamState(count=count, mu=mu_used, nu=nu, error=error)

    return optax.GradientTransformation(init_fn, update_fn)


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def scale_by_zero_one_adam(b1: float = 0.9,
                           b2: float = 0.999,
                           eps: float = 1e-8,
                           var_freeze_step: int = 100000,
                           var_update_scaler: int = 16) -> optax.GradientTransformation:
    """0/1 Adam (reference onebit/zoadam.py:14): like 1-bit Adam but with
    interval-scheduled variance updates instead of a hard freeze.

    The reference's local_step_scaler/clipper knobs schedule *local* (skipped
    inter-node) communication rounds for its compressed-allreduce backend;
    under SPMD the reduce is compiler-emitted each step, so that schedule has
    no analog here and the knobs are intentionally absent. No error-feedback
    buffer either: 0/1 Adam's momentum is exchanged uncompressed."""

    def init_fn(params):
        return ZeroOneAdamState(count=jnp.zeros([], jnp.int32),
                                mu=jax.tree_util.tree_map(jnp.zeros_like, params),
                                nu=jax.tree_util.tree_map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        count = state.count + 1
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        # variance updated every var_update_scaler steps (0/1 Adam policy)
        do_var = (count % var_update_scaler == 0) | (count <= var_freeze_step)
        nu = jax.tree_util.tree_map(
            lambda v, g: jnp.where(do_var, b2 * v + (1 - b2) * (g * g), v), state.nu, updates)
        c = count.astype(jnp.float32)
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / (1 - b1**c)) / (jnp.sqrt(v / (1 - b2**c)) + eps), mu, nu)
        return new_updates, ZeroOneAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def scale_by_onebit_lamb(b1: float = 0.9,
                         b2: float = 0.999,
                         eps: float = 1e-8,
                         freeze_step: int = 100000,
                         max_coeff: float = 10.0,
                         min_coeff: float = 0.01,
                         exchange_fn=None) -> optax.GradientTransformation:
    """1-bit LAMB (reference onebit/lamb.py:15): 1-bit Adam core + layerwise
    trust ratio clamped to [min_coeff, max_coeff]."""
    core = scale_by_onebit_adam(b1=b1, b2=b2, eps=eps, freeze_step=freeze_step,
                                exchange_fn=exchange_fn)

    def init_fn(params):
        return core.init(params)

    def update_fn(updates, state, params=None):
        upd, state = core.update(updates, state, params)

        def trust(u, p):
            pn = jnp.linalg.norm(p.astype(jnp.float32))
            un = jnp.linalg.norm(u.astype(jnp.float32))
            ratio = jnp.where(un > 0, pn / jnp.maximum(un, 1e-12), 1.0)
            ratio = jnp.clip(jnp.where(pn > 0, ratio, 1.0), min_coeff, max_coeff)
            return u * ratio

        upd = jax.tree_util.tree_map(trust, upd, params)
        return upd, state

    return optax.GradientTransformation(init_fn, update_fn)


def build_onebit_optimizer(name: str, params: Dict[str, Any], learning_rate,
                           exchange_fn=None) -> optax.GradientTransformation:
    betas = params.get("betas", (0.9, 0.999))
    eps = float(params.get("eps", 1e-8))
    weight_decay = float(params.get("weight_decay", 0.0))
    freeze_step = int(params.get("freeze_step", 100000))
    if name == "onebitadam":
        core = scale_by_onebit_adam(b1=betas[0], b2=betas[1], eps=eps, freeze_step=freeze_step,
                                    exchange_fn=exchange_fn)
    elif name == "zerooneadam":
        if exchange_fn is not None:
            raise ValueError("0/1 Adam's interval variance updates need the raw "
                             "gradients reduced — the compressed wire program "
                             "supports onebitadam/onebitlamb only")
        core = scale_by_zero_one_adam(b1=betas[0], b2=betas[1], eps=eps,
                                      var_freeze_step=int(params.get("var_freeze_step", freeze_step)),
                                      var_update_scaler=int(params.get("var_update_scaler", 16)))
    elif name == "onebitlamb":
        core = scale_by_onebit_lamb(b1=betas[0], b2=betas[1], eps=eps, freeze_step=freeze_step,
                                    max_coeff=float(params.get("max_coeff", 10.0)),
                                    min_coeff=float(params.get("min_coeff", 0.01)),
                                    exchange_fn=exchange_fn)
    else:
        raise ValueError(name)
    return optax.chain(
        core,
        optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        optax.scale_by_learning_rate(learning_rate),
    )
