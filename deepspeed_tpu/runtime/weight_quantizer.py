"""MoQ weight quantization.

Reference: ``runtime/weight_quantizer.py WeightQuantization`` — post/in-
training int8 quantization of model weights driven by the MoQ schedule
(optionally eigenvalue-informed). Built on the shared int8 blockwise
quantizer op (``ops/quantizer.py``)."""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.quantizer import dequantize_int8_blockwise, quantize_int8_blockwise


class WeightQuantization:

    def __init__(self, mlp_extra_grouping: bool = False, mp_size: int = 1):
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = mp_size

    def quantize_leaf(self, w, bits: int = 8, groups: int = 1) -> Tuple:
        """Quantize one weight; returns (values, scales). Extra grouping for
        MLP weights (reference: mlp_extra_grouping doubles groups)."""
        if bits != 8:
            raise NotImplementedError("int8 is the supported wire format")
        block = max(64, w.size // max(1, groups))
        return quantize_int8_blockwise(w, block_size=block) + (block, )

    def model_quantize(self, params, bits: int = 8, groups: int = 1,
                       predicate=None) -> Dict:
        """Fake-quantize every matching weight in a tree (round-trip through
        int8) — the deployable-accuracy check MoQ runs during training."""

        from ..parallel.tp import path_str

        def one(path, w):
            name = path_str(path)
            if not hasattr(w, "ndim") or w.ndim < 2:
                return w
            if predicate is not None and not predicate(name):
                return w
            g = groups * 2 if (self.mlp_extra_grouping and "mlp" in name) else groups
            values, scales, block = self.quantize_leaf(w, bits, g)
            return dequantize_int8_blockwise(values, scales, w.shape, block).astype(w.dtype)

        return jax.tree_util.tree_map_with_path(one, params)
