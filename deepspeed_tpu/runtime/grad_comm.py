"""Bucketed / quantized gradient-comm train program with microbatch overlap.

Reference: DeepSpeed's hook-driven bucketed reduce with ``overlap_comm``
(``runtime/zero/stage_1_and_2.py:897 reduce_independent_p_g_buckets_and_remove_grads``
/ ``:1364 reduce_ipg_grads``): as backward produces gradients, full buckets
are reduced asynchronously while the rest of backward runs. T3 (PAPERS.md)
makes the same point at a finer grain — the wall-clock win is collectives
overlapping the remaining compute, not the collectives themselves.

TPU shape: the engine's default gas>1 program accumulates the FULL gradient
tree across the microbatch ``lax.scan`` and lets GSPMD emit one implicit
reduce at the boundary. This module builds the alternative: a ``shard_map``
program over the data-parallel axes where

1. each microbatch computes LOCAL gradients (dp axes manual — no implicit
   psum),
2. the gradients are flattened into the comm planner's dtype-homogeneous
   buckets (``comm/bucketing.py``) and each bucket is REDUCE-SCATTERED on
   the spot (``overlap_comm``) — the scan carry holds the partially-reduced
   bucket *shards* (1/W of the tree per worker), and XLA's latency-hiding
   scheduler overlaps each bucket's collective with the remaining backward
   work of the same iteration; with ``overlap_comm: false`` the carry holds
   locally-accumulated full buckets and one bucketed exchange runs at the
   boundary,
3. at the boundary the reduced shards are all-gathered back (the second,
   independently-quantizable half of the two-step allreduce); under
   ZeRO-2 the gather is skipped — the scattered buckets exit the region
   sharded over the ZeRO axes (``ZeroShardingPlan.bucket_shardings``), i.e.
   the reduce-scatter lands directly on each worker's gradient shard.

The wire tier per bucket (fp32 / int8 / onebit) comes from
``gradient_comm.comm_quantization`` (+ per-dtype overrides). Error feedback
for the quantized tiers carries the residual across microbatches WITHIN a
step (the cross-step residual lives in the 1-bit optimizer's state for the
``onebit*`` optimizers; this program is optimizer-agnostic, so its residual
resets at each boundary — documented in docs/comm_compression.md).

Constraints (checked by ``grad_comm_supported``): pure-DP mesh (model/seq/
expert/pipe axes trivial), no fp16 loss scaling (the overflow check wants
the exact fp32 reduce), ZeRO stage <= 3, device optimizer (no host offload).
Stage 3 dispatches to the compiler-scheduled program in
``runtime/zero3_schedule.py`` — params live as 1/dp bucket shards and each
bucket's all-gather is woven into the scan one epoch ahead of use; its
gradients exit through the same ``reduce_scatter_bucket`` wire (the gather's
transpose), so the stage-2 numerics carry over bitwise on the fp32 tier.
"""

from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.bucketing import (BucketLayout, all_gather_bucket, flatten_buckets,
                              init_error_buckets, plan_buckets,
                              reduce_scatter_bucket, unflatten_buckets)
from ..utils.logging import log_dist
from .onebit_wire import _smap


def record_window_traffic(layout, dp_world: int, tier: str, block_size: int,
                          duration: float, steps: int,
                          op: str = "reduce_scatter"):
    """Window-amortized CommsLogger banking for the async pipeline: with
    per-step host timing removed (no ``float(loss)`` barrier to measure
    against), one host-timed sync window covers ``steps`` bucketed-comm
    dispatches — each is banked at the window-mean duration so
    ``calc_bw_log`` aggregates the same totals the per-step path reported."""
    if steps <= 0:
        return None
    from ..comm.bucketing import bucket_wire_bytes, record_bucket_traffic
    per_step = duration / steps
    stats = None
    for _ in range(steps):
        stats = record_bucket_traffic(layout, dp_world, tier, block_size,
                                      duration=per_step, op=op)
    # observability registry mirror (independent of the CommsLogger gate):
    # wire volume and dispatch count for comm-vs-compute attribution
    from ..observability import get_registry
    reg = get_registry()
    wire = bucket_wire_bytes(layout, dp_world, tier, block_size)["wire_bytes"]
    reg.counter(
        "ds_train_comm_bytes_total",
        "Bucketed gradient-collective wire bytes (post-quantization)"
    ).inc(float(wire) * steps)
    reg.counter(
        "ds_train_comm_dispatches_total",
        "Bucketed gradient-collective step dispatches banked"
    ).inc(steps)
    return stats


def grad_comm_supported(engine) -> bool:
    cfg = engine._config
    ctx = engine.mesh_ctx
    dp = sum(ctx.axis_size(a) > 1 for a in ("data", "fsdp"))
    if cfg.zero_config.stage >= 3:
        # stage 3 runs the scheduled param-store program, which needs the
        # store to have been installed at init (its own support predicate:
        # additionally no offload, no composed TP, ZeRO axes == dp world)
        from .zero3_schedule import zero3_store_supported
        return (zero3_store_supported(engine)
                and getattr(engine, "_zero3_store", None) is not None)
    return (cfg.zero_config.stage <= 2
            and not cfg.fp16_enabled
            and dp >= 1  # something to reduce over
            and all(ctx.axis_size(a) == 1 for a in ("model", "seq", "expert", "pipe")))


def build_grad_comm_step(engine, apply_step):
    """Compile the bucketed-comm train-batch program for ``engine``.

    ``apply_step``: the engine's untraced optimizer-apply body
    ``(params, acc, opt_state, scale_state) -> (new_params, new_opt, zeroed,
    new_scale_state, overflow, gnorm)`` — reused so the update math is
    byte-for-byte the default path's.

    Returns ``(step_fn, layout)`` where ``step_fn`` has the engine's fused
    train-batch signature ``(params, opt_state, scale_state, stacked_args,
    static_kv)``.
    """
    if not grad_comm_supported(engine):
        raise ValueError(
            "the bucketed gradient-comm program needs a pure data-parallel "
            "mesh, ZeRO stage <= 3, bf16/fp32, and a device optimizer "
            "(stage 3 additionally: no offload, no composed tensor-parallel, "
            "ZeRO axes spanning the full dp world)")
    if engine.zero_plan.stage >= 3:
        from .zero3_schedule import build_zero3_step
        return build_zero3_step(engine, apply_step)
    cfg = engine._config
    gc = cfg.gradient_comm_config
    ctx = engine.mesh_ctx
    mesh = ctx.mesh
    dp_axes = tuple(a for a in ("data", "fsdp") if ctx.axis_size(a) > 1)
    ax = dp_axes if len(dp_axes) > 1 else dp_axes[0]
    w = ctx.axis_size(dp_axes)
    gas = engine.gradient_accumulation_steps()
    compute_dtype = engine.compute_dtype
    apply_fn = engine.apply_fn
    loss_fn = engine._loss_fn
    block = int(gc.quantization_block_size)
    overlap = bool(gc.overlap_comm)
    feedback = bool(gc.error_feedback)

    # pad every bucket so both the dp split and the quantization blocks
    # divide; layout is planned once, against the param tree (grads mirror it)
    layout = plan_buckets(engine.params, gc.bucket_size_mb,
                          pad_multiple=w * block)
    tiers = [gc.tier_for_dtype(b.dtype) for b in layout.buckets]
    quantized = [t != "fp32" for t in tiers]
    bucket_shardings = engine.zero_plan.bucket_shardings(layout)
    # ZeRO-2: leave the reduced buckets scattered over the ZeRO axes — the
    # reduce-scatter IS the gradient partitioning; stage 0/1 gathers back
    # (replicated grads) inside the region
    scatter_exit = engine.zero_plan.stage >= 2 and bool(engine.zero_plan.zero_axes)

    from .engine import _extract_loss

    def local_scaled_loss(params, margs):
        cparams = jax.tree_util.tree_map(
            lambda x: x.astype(compute_dtype), params)
        out = apply_fn(cparams, *margs)
        if loss_fn is not None:
            loss = loss_fn(out)
        else:
            loss, _ = _extract_loss(out)
        return loss.astype(jnp.float32) / gas, loss

    def region(params, stacked_args):
        """dp axes manual: params/full replicated, batch locally sharded."""

        def micro(carry, margs):
            shards, errs, loss_sum = carry
            (_, loss), grads = jax.value_and_grad(
                local_scaled_loss, has_aux=True)(params, margs)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            buckets = flatten_buckets(grads, layout)
            if feedback:
                buckets = [b + e for b, e in zip(buckets, errs)]
            new_shards, new_errs = [], []
            for b, s, e, tier, q in zip(buckets, shards, errs, tiers, quantized):
                if overlap:
                    # reduce THIS microbatch's bucket now; the collective
                    # overlaps the rest of this iteration's backward
                    red, resid = reduce_scatter_bucket(b, ax, tier, block)
                    new_shards.append(s + red)
                else:
                    # boundary mode: accumulate locally, exchange once below
                    new_shards.append(s + b)
                    resid = jnp.zeros_like(e)
                new_errs.append(resid if (feedback and q) else
                                jnp.zeros_like(e))
            return (new_shards, new_errs,
                    loss_sum + loss.astype(jnp.float32)), None

        shard_len = [b.padded_size // w if overlap else b.padded_size
                     for b in layout.buckets]
        init = ([jnp.zeros((n, ), jnp.float32) for n in shard_len],
                init_error_buckets(layout),
                jnp.float32(0.0))
        (shards, _, loss_sum), _ = lax.scan(micro, init, stacked_args)
        if not overlap:
            shards = [reduce_scatter_bucket(b, ax, tier, block)[0]
                      for b, tier in zip(shards, tiers)]
        # psum_scatter summed over workers; the grad semantic is the mean
        shards = [s / w for s in shards]
        if scatter_exit:
            out_buckets = shards  # exit sharded: P(ax) concatenates them
        else:
            out_buckets = [all_gather_bucket(s, ax, tier, block)
                           for s, tier in zip(shards, tiers)]
        # match train_batch_steps' reported loss: microbatch mean, dp mean
        loss_mean = lax.pmean(loss_sum / gas, ax)
        return loss_mean, out_buckets

    def _arg_spec(leaf):
        shape = getattr(leaf, "shape", ())
        # dim 0 is the microbatch axis; the batch splits on dim 1 (same rule
        # as ZeroShardingPlan.batch_sharding(stacked=True))
        if len(shape) < 2 or shape[1] % w != 0:
            return P()
        return P(None, ax)

    bucket_out_spec = [P(ax) if scatter_exit else P() for _ in layout.buckets]

    def step(params, opt_state, scale_state, stacked_args, static_kv):
        assert not static_kv, "bucketed grad-comm path takes positional batch arrays only"
        in_specs = (P(), jax.tree_util.tree_map(_arg_spec, stacked_args))
        fn = _smap(region, mesh, in_specs, (P(), bucket_out_spec), dp_axes)
        loss, buckets = fn(params, stacked_args)
        buckets = [lax.with_sharding_constraint(b, s)
                   for b, s in zip(buckets, bucket_shardings)]
        acc = unflatten_buckets(buckets, layout, example_tree=params)
        new_params, new_opt, _, new_scale_state, overflow, gnorm = apply_step(
            params, acc, opt_state, scale_state)
        return loss, new_params, new_opt, new_scale_state, overflow, gnorm

    from .loss_scaler import LossScaleState
    repl = NamedSharding(mesh, P())
    jitted = jax.jit(
        step, donate_argnums=(0, 1), static_argnums=(4, ),
        out_shardings=(None, engine.param_shardings, engine.opt_state_shardings,
                       LossScaleState(*engine.scale_state_shardings), repl, repl))
    log_dist(
        f"bucketed grad-comm program built: {len(layout.buckets)} buckets "
        f"(dtypes {[str(np.dtype(b.dtype)) for b in layout.buckets]}, tiers "
        f"{tiers}), overlap={'per-microbatch reduce-scatter' if overlap else 'boundary'}, "
        f"zero_scatter_exit={scatter_exit}, dp axes {dp_axes}", ranks=[0])
    return jitted, layout
