"""``deepspeed.zero`` API surface.

Reference: ``deepspeed/runtime/zero/partition_parameters.py`` (``Init :816``,
``GatheredParameters :2065``, ``register_external_parameter :128``). The
torch implementation monkey-patches ``nn.Module.__init__`` so parameters are
born partitioned; under pjit the same outcome is native: the engine places
every parameter according to the ZeRO sharding plan at ``_init_state``
(``runtime/zero_sharding.py``), and XLA gathers shards on demand inside the
compiled step. These shims keep user code that wraps model construction in
``zero.Init()`` / reads params under ``GatheredParameters()`` working
unchanged — they are documented identities, not stubs: the *semantics*
(sharded residency, gather-for-use) are provided by the sharding plan.
"""

import contextlib
from typing import Any, Iterable, Optional

import jax


class Init(contextlib.AbstractContextManager):
    """Context manager for sharded model construction (reference ``Init``).

    Under jax, module construction is shape-only (flax ``init`` produces the
    params afterwards), so there is nothing to intercept: pass the produced
    params to :func:`deepspeed_tpu.initialize` and the ZeRO plan shards them.
    Accepts and records the reference's kwargs (``remote_device``,
    ``config_dict_or_path``…) so launch scripts port without edits.
    """

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None, sequence_data_parallel_group=None,
                 param_swapper=None):
        self.enabled = enabled
        self.remote_device = remote_device
        self.config = config_dict_or_path if config_dict_or_path is not None else config
        self.dtype = dtype

    def __exit__(self, *exc):
        return False


@contextlib.contextmanager
def GatheredParameters(params: Any, modifier_rank: Optional[int] = None,
                       fwd_module=None, enabled: bool = True):
    """Reference ``GatheredParameters``: materialize sharded params for host
    access. jax arrays are already addressable transparently (XLA gathers
    shards on read); yield them unchanged."""
    yield params


def register_external_parameter(module, parameter) -> None:
    """Reference ``partition_parameters.py:128``: mark a param used outside
    its owning module so the coordinator prefetches it. XLA's scheduler sees
    every use in the jaxpr — no registry needed."""
    return None


def unregister_external_parameter(module, parameter) -> None:
    return None
