"""Checkpoint engines.

Rebuild of reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``
(the create/save/load/commit ABC) with an orbax-backed implementation:
sharded arrays are written/restored natively (each host writes its shards),
which subsumes the reference's per-DP-rank ZeRO shard files
(``engine.py:3528 _save_zero_checkpoint``) — orbax metadata records the
sharding, and restore-with-different-topology covers elastic resume.

Crash consistency (resilience tentpole): every committed checkpoint carries
an integrity manifest (``ds_manifest.json``: per-entry byte sizes + CRC32)
and a commit marker (``ds_commit``) written LAST. A directory without the
marker is a torn write by definition; a directory whose entries disagree
with the manifest is corrupt. ``verify_checkpoint`` checks both,
``find_latest_valid_checkpoint`` scans a save dir newest-first and
quarantines bad tags, and ``prune_checkpoints`` enforces a ``keep_last_n``
retention policy — all storage mutations bounded by retry-with-backoff.
"""

import json
import os
import re
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import logger
from ..utils.retry import retry_with_backoff
from ..utils.fault_injection import (get_fault_injector, tear_checkpoint_dir,
                                     corrupt_file_in)

MANIFEST_FILE = "ds_manifest.json"
COMMIT_MARKER_FILE = "ds_commit"
QUARANTINE_SUFFIX = ".quarantined"
MANIFEST_VERSION = 1


def _ckpt_hist(kind: str):
    """Registry histograms for checkpoint IO wall time (save includes the
    orbax write + host-state flush on the sync path, only the dispatch on
    the async path — commit() carries the wait there)."""
    from ..observability import get_registry
    return get_registry().histogram(
        f"ds_checkpoint_{kind}_seconds",
        f"Wall seconds per checkpoint {kind}", lo=1e-4, hi=1e4,
        buckets_per_decade=5)


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed manifest verification (torn or corrupt)."""


# ---------------------------------------------------------------------------
# integrity manifest
# ---------------------------------------------------------------------------


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def _manifest_entries(path: str) -> Dict[str, Dict[str, int]]:
    entries = {}
    for root, _, files in os.walk(path):
        for f in files:
            if f in (MANIFEST_FILE, COMMIT_MARKER_FILE):
                continue
            p = os.path.join(root, f)
            rel = os.path.relpath(p, path)
            entries[rel] = {"size": os.path.getsize(p), "crc32": _crc32_file(p)}
    return entries


def write_manifest(path: str, tag: Any) -> None:
    """Write the integrity manifest, then the commit marker — in that order,
    each atomically (tmp + rename): a crash at any point leaves either no
    marker (torn, detectable) or a fully consistent checkpoint."""
    manifest = {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "entries": _manifest_entries(path),
    }

    def _write():
        tmp = os.path.join(path, MANIFEST_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, MANIFEST_FILE))

    retry_with_backoff(_write, desc=f"write manifest {path}")

    def _mark():
        tmp = os.path.join(path, COMMIT_MARKER_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, COMMIT_MARKER_FILE))

    retry_with_backoff(_mark, desc=f"write commit marker {path}")


def verify_checkpoint(path: str, require_manifest: bool = True) -> Tuple[bool, str]:
    """Integrity-check one checkpoint directory. Returns ``(ok, reason)``.

    ``require_manifest=False`` grandfathers pre-manifest checkpoints: a dir
    with NO manifest and NO marker passes (legacy), but a manifest that is
    present must verify and a manifest without its marker is a torn write."""
    if not os.path.isdir(path):
        return False, "missing directory"
    has_manifest = os.path.exists(os.path.join(path, MANIFEST_FILE))
    has_marker = os.path.exists(os.path.join(path, COMMIT_MARKER_FILE))
    if not has_manifest and not has_marker:
        if require_manifest:
            return False, "uncommitted (no manifest/commit marker)"
        return True, "legacy checkpoint (no manifest); verification skipped"
    if not has_marker:
        return False, "torn write (manifest present but no commit marker)"
    if not has_manifest:
        return False, "commit marker without manifest"
    try:
        with open(os.path.join(path, MANIFEST_FILE)) as f:
            manifest = json.load(f)
        entries = manifest["entries"]
    except (json.JSONDecodeError, KeyError, OSError) as e:
        return False, f"unreadable manifest: {e}"
    for rel, meta in entries.items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return False, f"missing entry {rel}"
        size = os.path.getsize(p)
        if size != meta["size"]:
            return False, f"size mismatch on {rel}: {size} != {meta['size']}"
        if _crc32_file(p) != meta["crc32"]:
            return False, f"checksum mismatch on {rel}"
    return True, "ok"


# ---------------------------------------------------------------------------
# save-dir scanning / quarantine / retention
# ---------------------------------------------------------------------------

_STEP_RE = re.compile(r"(\d+)\s*$")


def _tag_sort_key(load_dir: str, tag: str):
    """Newest-first ordering: numeric step suffix (global_step<N>) wins,
    falling back to directory mtime, then name."""
    m = _STEP_RE.search(tag)
    step = int(m.group(1)) if m else -1
    try:
        mtime = os.path.getmtime(os.path.join(load_dir, tag))
    except OSError:
        mtime = 0.0
    return (step, mtime, tag)


def scan_tags(load_dir: str) -> List[str]:
    """Checkpoint tags under ``load_dir``, newest first (quarantined dirs
    excluded)."""
    if not os.path.isdir(load_dir):
        return []
    tags = [d for d in os.listdir(load_dir)
            if os.path.isdir(os.path.join(load_dir, d))
            and not d.endswith(QUARANTINE_SUFFIX)]
    return sorted(tags, key=lambda t: _tag_sort_key(load_dir, t), reverse=True)


def quarantine_checkpoint(load_dir: str, tag: str) -> Optional[str]:
    """Move a bad checkpoint dir aside (``<tag>.quarantined[.N]``) so scans
    never retry it; kept (not deleted) as evidence for postmortems."""
    src = os.path.join(load_dir, tag)
    dst = src + QUARANTINE_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{QUARANTINE_SUFFIX}.{n}"
    try:
        retry_with_backoff(lambda: os.replace(src, dst),
                           desc=f"quarantine {src}")
    except Exception as e:  # noqa: BLE001 — quarantine is best-effort
        logger.warning(f"could not quarantine {src}: {e}")
        return None
    logger.warning(f"quarantined corrupt checkpoint {src} -> {dst}")
    return dst


def find_latest_valid_checkpoint(load_dir: str, quarantine: bool = True,
                                 require_manifest: bool = True) -> Optional[str]:
    """Newest tag under ``load_dir`` that passes manifest verification,
    falling back through older tags.

    Only *provably* bad dirs (a manifest or commit marker is present but
    verification fails: torn or corrupt) are quarantined; dirs with neither
    file are merely skipped when ``require_manifest`` — they could be a
    legacy-format checkpoint or another process's in-flight save, and a
    crash-time scan must not destroy either."""
    for tag in scan_tags(load_dir):
        path = os.path.join(load_dir, tag)
        ok, reason = verify_checkpoint(path, require_manifest=require_manifest)
        if ok:
            return tag
        provable = (os.path.exists(os.path.join(path, MANIFEST_FILE))
                    or os.path.exists(os.path.join(path, COMMIT_MARKER_FILE)))
        logger.warning(f"checkpoint {tag} failed verification ({reason}); "
                       "falling back to an older tag")
        if quarantine and provable:
            quarantine_checkpoint(load_dir, tag)
    return None


def read_latest_tag(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, "latest")
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        tag = f.read().strip()
    return tag or None


def write_latest_tag(load_dir: str, tag: Any) -> None:
    """Atomic ``latest`` pointer update (tmp + rename): readers never see a
    half-written tag."""

    def _write():
        tmp = os.path.join(load_dir, "latest.tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(load_dir, "latest"))

    retry_with_backoff(_write, desc=f"write latest pointer in {load_dir}")


def prune_checkpoints(save_dir: str, keep_last_n: int,
                      protect: Tuple[str, ...] = ()) -> List[str]:
    """Retention GC: keep the ``keep_last_n`` newest committed tags (plus
    anything in ``protect`` and the current ``latest`` target), delete the
    rest with bounded retry. Returns the deleted tags. ``keep_last_n <= 0``
    keeps everything."""
    if keep_last_n <= 0:
        return []
    keep = set(protect)
    latest = read_latest_tag(save_dir)
    if latest:
        keep.add(latest)
    tags = scan_tags(save_dir)  # newest first
    committed = [t for t in tags
                 if os.path.exists(os.path.join(save_dir, t, COMMIT_MARKER_FILE))]
    keep.update(committed[:keep_last_n])
    deleted = []
    for tag in committed[keep_last_n:]:
        if tag in keep:
            continue
        path = os.path.join(save_dir, tag)
        try:
            retry_with_backoff(lambda p=path: shutil.rmtree(p),
                               desc=f"prune checkpoint {path}")
            deleted.append(tag)
        except Exception as e:  # noqa: BLE001 — GC failure must not kill training
            logger.warning(f"retention GC could not delete {path}: {e}")
    if deleted:
        logger.info(f"retention (keep_last_n={keep_last_n}): pruned {deleted}")
    return deleted


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------


class CheckpointEngine:
    """ABC (reference checkpoint_engine.py:9)."""

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        ...

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        raise NotImplementedError

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded async-capable checkpointing via orbax.

    The reference's torch engine writes one file per rank; here a single
    logical checkpoint directory holds OCDBT-sharded arrays + a JSON sidecar
    for host state (step counters, scheduler, rng, client state).

    ``commit(tag)`` is the durability barrier AND the integrity seal: it
    waits out any async write, persists pending host state, then writes the
    manifest and (last) the commit marker. It returns False — and the caller
    must NOT advance the ``latest`` pointer — when the checkpoint could not
    be sealed.
    """

    HOST_STATE_FILE = "ds_host_state.pkl"
    LEGACY_HOST_STATE_FILE = "ds_host_state.json"

    def __init__(self, config_params=None, use_async: bool = False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()
        self._async = use_async
        self._pending_path = None  # path of the save awaiting commit()

    def create(self, tag):
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is about to be saved!")

    def save(self, state_dict: Dict[str, Any], path: str, host_state: Optional[Dict] = None):
        import time
        t0 = time.perf_counter()
        path = os.path.abspath(path)
        self._ckptr.save(path, state_dict, force=True)
        self._pending_path = path
        if self._async:
            # orbax materializes the dir atomically (tmp → rename) when the
            # async write completes; host state must wait for commit()
            self._pending_host_state = (path, host_state)
            _ckpt_hist("save").record(time.perf_counter() - t0)
            return path
        self._ckptr.wait_until_finished()
        self._write_host_state(path, host_state)
        _ckpt_hist("save").record(time.perf_counter() - t0)
        return path

    def _write_host_state(self, path, host_state):
        if host_state is not None:
            # pickle, not JSON: the reference torch.save()s arbitrary client
            # state (engine.py:3109) — numpy rng states etc. must round-trip
            import pickle
            with open(os.path.join(path, self.HOST_STATE_FILE), "wb") as f:
                pickle.dump(host_state, f)

    def load(self, path: str, map_location=None, target=None, verify: bool = True):
        """Restore; `target` is an abstract pytree (jax.ShapeDtypeStruct with
        shardings) directing placement — omit to restore as numpy.

        ``verify=True`` checks the integrity manifest first (legacy dirs
        without one pass) and raises :class:`CheckpointCorruptionError`
        instead of letting orbax deserialize torn data."""
        import time
        t0 = time.perf_counter()
        path = os.path.abspath(path)
        if verify:
            ok, reason = verify_checkpoint(path, require_manifest=False)
            if not ok:
                raise CheckpointCorruptionError(f"{path}: {reason}")
        if target is not None:
            restored = self._ckptr.restore(path, target)
        else:
            restored = self._ckptr.restore(path)
        host_state = None
        hs_path = os.path.join(path, self.HOST_STATE_FILE)
        legacy = os.path.join(path, self.LEGACY_HOST_STATE_FILE)
        if os.path.exists(hs_path):
            import pickle
            with open(hs_path, "rb") as f:
                host_state = pickle.load(f)
        elif os.path.exists(legacy):
            with open(legacy) as f:
                host_state = json.load(f)
        _ckpt_hist("load").record(time.perf_counter() - t0)
        return restored, host_state

    def commit(self, tag) -> bool:
        if self._async:
            self._ckptr.wait_until_finished()
            pending = getattr(self, "_pending_host_state", None)
            if pending is not None:
                self._write_host_state(*pending)
                self._pending_host_state = None
        path = self._pending_path
        self._pending_path = None
        if path is not None and jax.process_index() == 0:
            fi = get_fault_injector()
            torn = fi.fire("checkpoint.torn_write", path=path, tag=tag)
            if torn is not None:
                # simulated crash mid-write: a truncated entry and no
                # manifest/marker — the load path must detect and fall back
                tear_checkpoint_dir(path,
                                    truncate_to=int(torn.get("truncate_to", 16)))
                logger.error(f"[OrbaxCheckpointEngine] commit of {tag} failed "
                             "(torn write)")
                return False
            try:
                write_manifest(path, tag)
            except Exception as e:  # noqa: BLE001 — seal failure = no commit
                logger.error(f"[OrbaxCheckpointEngine] could not seal {tag}: {e}")
                return False
            corrupt = fi.fire("checkpoint.corrupt", path=path, tag=tag)
            if corrupt is not None:
                # silent post-commit bit-rot: manifest verification at load
                # time is the only thing standing between this and a bad
                # resume — the marker stays, the data lies
                corrupt_file_in(path, seed=fi.seed)
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready now!")
        return True


class AsyncCheckpointEngine(OrbaxCheckpointEngine):
    """Tiered/async checkpointing (reference nebula_checkpoint_engine.py):
    ``save`` returns once the snapshot is staged (orbax async write continues
    in the background); ``commit`` is the durability barrier. Training
    overlaps the serialization — the Nebula value proposition, natively."""

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)
