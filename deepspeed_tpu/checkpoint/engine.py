"""Checkpoint engines.

Rebuild of reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``
(the create/save/load/commit ABC) with an orbax-backed implementation:
sharded arrays are written/restored natively (each host writes its shards),
which subsumes the reference's per-DP-rank ZeRO shard files
(``engine.py:3528 _save_zero_checkpoint``) — orbax metadata records the
sharding, and restore-with-different-topology covers elastic resume.
"""

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import logger


class CheckpointEngine:
    """ABC (reference checkpoint_engine.py:9)."""

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        ...

    def save(self, state_dict, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        raise NotImplementedError

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded async-capable checkpointing via orbax.

    The reference's torch engine writes one file per rank; here a single
    logical checkpoint directory holds OCDBT-sharded arrays + a JSON sidecar
    for host state (step counters, scheduler, rng, client state).
    """

    HOST_STATE_FILE = "ds_host_state.pkl"
    LEGACY_HOST_STATE_FILE = "ds_host_state.json"

    def __init__(self, config_params=None, use_async: bool = False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()
        self._async = use_async

    def create(self, tag):
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is about to be saved!")

    def save(self, state_dict: Dict[str, Any], path: str, host_state: Optional[Dict] = None):
        path = os.path.abspath(path)
        self._ckptr.save(path, state_dict, force=True)
        if self._async:
            # orbax materializes the dir atomically (tmp → rename) when the
            # async write completes; host state must wait for commit()
            self._pending_host_state = (path, host_state)
            return path
        self._ckptr.wait_until_finished()
        self._write_host_state(path, host_state)
        return path

    def _write_host_state(self, path, host_state):
        if host_state is not None:
            # pickle, not JSON: the reference torch.save()s arbitrary client
            # state (engine.py:3109) — numpy rng states etc. must round-trip
            import pickle
            with open(os.path.join(path, self.HOST_STATE_FILE), "wb") as f:
                pickle.dump(host_state, f)

    def load(self, path: str, map_location=None, target=None):
        """Restore; `target` is an abstract pytree (jax.ShapeDtypeStruct with
        shardings) directing placement — omit to restore as numpy."""
        path = os.path.abspath(path)
        if target is not None:
            restored = self._ckptr.restore(path, target)
        else:
            restored = self._ckptr.restore(path)
        host_state = None
        hs_path = os.path.join(path, self.HOST_STATE_FILE)
        legacy = os.path.join(path, self.LEGACY_HOST_STATE_FILE)
        if os.path.exists(hs_path):
            import pickle
            with open(hs_path, "rb") as f:
                host_state = pickle.load(f)
        elif os.path.exists(legacy):
            with open(legacy) as f:
                host_state = json.load(f)
        return restored, host_state

    def commit(self, tag):
        if self._async:
            self._ckptr.wait_until_finished()
            pending = getattr(self, "_pending_host_state", None)
            if pending is not None:
                self._write_host_state(*pending)
                self._pending_host_state = None
        logger.info(f"[OrbaxCheckpointEngine] Checkpoint {tag} is ready now!")
        return True


class AsyncCheckpointEngine(OrbaxCheckpointEngine):
    """Tiered/async checkpointing (reference nebula_checkpoint_engine.py):
    ``save`` returns once the snapshot is staged (orbax async write continues
    in the background); ``commit`` is the durability barrier. Training
    overlaps the serialization — the Nebula value proposition, natively."""

    def __init__(self, config_params=None):
        super().__init__(config_params, use_async=True)
