"""Offline fp32 state-dict reconstruction from an engine checkpoint.

Reference: ``deepspeed/utils/zero_to_fp32.py`` — the user-facing script that
merges per-rank ZeRO shards into one consolidated fp32 state dict. With
orbax, shards merge at read time, so this reduces to: restore as numpy,
take the fp32 master params, dump a flat npz (plus the same
``get_fp32_state_dict_from_zero_checkpoint`` programmatic API).
"""

import os
from typing import Dict, Optional

import numpy as np

from ..utils.logging import logger
from .universal import _flatten


def _resolve_tag(checkpoint_dir: str, tag: Optional[str]) -> str:
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
        else:
            raise ValueError(f"no 'latest' file in {checkpoint_dir}; pass tag explicitly")
    return os.path.join(checkpoint_dir, tag)


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Flat {dotted.param.path: fp32 array} (reference zero_to_fp32.py
    get_fp32_state_dict_from_zero_checkpoint)."""
    from .engine import OrbaxCheckpointEngine
    path = _resolve_tag(checkpoint_dir, tag)
    state, _ = OrbaxCheckpointEngine().load(path)
    return {k: np.asarray(v, dtype=np.float32) for k, v in _flatten(state["params"]).items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: Optional[str] = None) -> str:
    """Write the consolidated fp32 params as one .npz (reference writes
    pytorch_model.bin)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    np.savez(output_file, **sd)
    logger.info(f"saved {len(sd)} fp32 tensors to {output_file}")
    return output_file


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="Extract fp32 weights from a checkpoint")
    ap.add_argument("checkpoint_dir")
    ap.add_argument("output_file")
    ap.add_argument("-t", "--tag", default=None)
    args = ap.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, args.tag)


if __name__ == "__main__":
    main()
