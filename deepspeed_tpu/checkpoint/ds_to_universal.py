"""Reference CLI name alias: ``python -m deepspeed_tpu.checkpoint.ds_to_universal``
(reference ``deepspeed/checkpoint/ds_to_universal.py:469 main``) — forwards to
the universal-checkpoint converter in ``universal.py``."""

from .universal import main

if __name__ == "__main__":
    raise SystemExit(main())
