"""Universal checkpoint: any→any parallelism conversion.

Reference: ``deepspeed/checkpoint/ds_to_universal.py`` (extract ZeRO shards →
merge TP slices → per-parameter fp32 "universal" fragments) and
``checkpoint/universal_checkpoint.py:22 load_hp_checkpoint_state``.

TPU-side most of the reference machinery is already subsumed: orbax stores
full *logical* arrays (sharding is metadata, not file layout), so "merge
shards" is a no-op. What remains — and is rebuilt here — is the *layout
contract*: a checkpoint exploded into one directory per parameter holding
fp32 master weight + optimizer moments, loadable into ANY later topology
(different mesh, different optimizer partitioning, even a different
framework). That contract is what makes cross-cluster / cross-revision
resume possible, so we keep it file-for-file.

Layout (matches the reference's universal layout semantically):
    <out>/zero/<param.path>/fp32.npy
    <out>/zero/<param.path>/exp_avg.npy       (when Adam-family state exists)
    <out>/zero/<param.path>/exp_avg_sq.npy
    <out>/universal_meta.json                 {step, param list, source}
"""

import json
import os
import shutil
from typing import Any, Dict, Optional

import numpy as np
import jax

from ..utils.logging import logger

_SEP = "."


def _flatten(tree, prefix=()):
    """Dict/list pytree → {dotted.path: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, prefix + (str(k), )))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, prefix + (str(i), )))
    else:
        out[_SEP.join(prefix)] = tree
    return out


def _unflatten_into(flat: Dict[str, Any], target_tree):
    """Place flat {path: array} into the structure of target_tree.

    Rebuilt by recursing the *target* structure keyed by path — zipping a
    flattened-dict insertion order against ``tree_structure`` (which sorts
    dict keys) silently scrambles leaves whenever insertion order isn't
    sorted (e.g. ``layers_2`` vs ``layers_10``, ``norm`` vs ``lm_head``).
    """
    flat_t = _flatten(target_tree)
    missing = [k for k in flat_t if k not in flat]
    if missing:
        raise KeyError(f"universal checkpoint missing parameters: {missing[:5]}"
                       f"{'...' if len(missing) > 5 else ''}")

    def build(node, prefix):
        if isinstance(node, dict):
            return {k: build(v, prefix + (str(k), )) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            seq = [build(v, prefix + (str(i), )) for i, v in enumerate(node)]
            if hasattr(node, "_fields"):  # namedtuple
                return type(node)(*seq)
            return type(node)(seq)
        return flat[_SEP.join(prefix)]

    return build(target_tree, ())


def _find_adam_moments(opt_state) -> Optional[Any]:
    """Locate the ScaleByAdamState-like entry (has mu/nu pytrees) in an optax
    chain state. Returns (mu, nu, count) or None."""
    def probe(node):
        # live optax state: ScaleByAdamState namedtuple; orbax numpy restore:
        # the same structure as nested dicts keyed by field name
        if hasattr(node, "mu") and hasattr(node, "nu"):
            return node.mu, node.nu, getattr(node, "count", None)
        if isinstance(node, dict) and "mu" in node and "nu" in node:
            return node["mu"], node["nu"], node.get("count")
        if isinstance(node, (list, tuple)):
            for item in node:
                found = probe(item)
                if found is not None:
                    return found
        if isinstance(node, dict):
            for item in node.values():
                found = probe(item)
                if found is not None:
                    return found
        return None
    return probe(opt_state)


def ds_to_universal(ckpt_path: str, output_dir: str) -> str:
    """Convert an engine checkpoint (orbax dir saved by save_checkpoint) to
    the universal layout (reference ds_to_universal.py:469 main)."""
    from .engine import OrbaxCheckpointEngine
    eng = OrbaxCheckpointEngine()
    state, host_state = eng.load(ckpt_path)  # numpy restore, no target

    params = state["params"]
    flat_params = _flatten(params)
    moments = _find_adam_moments(state.get("opt_state"))

    zero_dir = os.path.join(output_dir, "zero")
    if os.path.exists(zero_dir):
        shutil.rmtree(zero_dir)
    os.makedirs(zero_dir)

    for name, w in flat_params.items():
        pdir = os.path.join(zero_dir, name)
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, "fp32.npy"), np.asarray(w, dtype=np.float32))
    if moments is not None:
        mu, nu, count = moments
        for fname, tree in (("exp_avg.npy", mu), ("exp_avg_sq.npy", nu)):
            for name, m in _flatten(tree).items():
                np.save(os.path.join(zero_dir, name, fname),
                        np.asarray(m, dtype=np.float32))

    meta = {
        "step": int(host_state.get("global_steps", 0)) if host_state else 0,
        "params": sorted(flat_params.keys()),
        "has_optim_states": moments is not None,
        "source": os.path.abspath(ckpt_path),
    }
    with open(os.path.join(output_dir, "universal_meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    logger.info(f"universal checkpoint written: {output_dir} ({len(flat_params)} params)")
    return output_dir


def load_universal(universal_dir: str, fname: str = "fp32.npy") -> Dict[str, np.ndarray]:
    """Read one fragment kind for all params → {dotted.path: array}."""
    zero_dir = os.path.join(universal_dir, "zero")
    with open(os.path.join(universal_dir, "universal_meta.json")) as f:
        meta = json.load(f)
    out = {}
    for name in meta["params"]:
        path = os.path.join(zero_dir, name, fname)
        if os.path.exists(path):
            out[name] = np.load(path)
    return out


def load_universal_into(universal_dir: str, params_target, opt_state_target=None):
    """Reconstruct (params, opt_state) pytrees shaped like the targets from a
    universal dir (reference universal_checkpoint.py:22
    load_hp_checkpoint_state — per-param fragment mapping)."""
    with open(os.path.join(universal_dir, "universal_meta.json")) as f:
        meta = json.load(f)
    params = _unflatten_into(load_universal(universal_dir, "fp32.npy"), params_target)
    opt_state = None
    if opt_state_target is not None and meta.get("has_optim_states"):
        moments = _find_adam_moments(opt_state_target)
        if moments is not None:
            mu_t, nu_t, _ = moments
            mu = _unflatten_into(load_universal(universal_dir, "exp_avg.npy"), mu_t)
            nu = _unflatten_into(load_universal(universal_dir, "exp_avg_sq.npy"), nu_t)

            step = int(meta.get("step", 0))

            def swap(node):
                if hasattr(node, "mu") and hasattr(node, "nu"):
                    repl = {"mu": mu, "nu": nu}
                    if hasattr(node, "count"):  # bias-correction step counter
                        repl["count"] = np.asarray(step, dtype=np.int32)
                    return node._replace(**repl)
                if isinstance(node, tuple) and not hasattr(node, "_fields"):
                    return tuple(swap(x) for x in node)
                if isinstance(node, list):
                    return [swap(x) for x in node]
                return node
            opt_state = swap(opt_state_target)
    return params, opt_state, meta


def main(argv=None):
    """CLI: python -m deepspeed_tpu.checkpoint.universal <ckpt> <out>."""
    import argparse
    ap = argparse.ArgumentParser(description="DeepSpeed-TPU universal checkpoint converter")
    ap.add_argument("input_folder", help="engine checkpoint dir (a tag dir)")
    ap.add_argument("output_folder", help="universal checkpoint output dir")
    args = ap.parse_args(argv)
    ds_to_universal(args.input_folder, args.output_folder)


if __name__ == "__main__":
    main()
