from .engine import CheckpointEngine, OrbaxCheckpointEngine, AsyncCheckpointEngine
from .universal import ds_to_universal, load_universal, load_universal_into
from .zero_to_fp32 import (get_fp32_state_dict_from_zero_checkpoint,
                           convert_zero_checkpoint_to_fp32_state_dict)
