from .engine import (CheckpointEngine, OrbaxCheckpointEngine, AsyncCheckpointEngine,
                     CheckpointCorruptionError, MANIFEST_FILE, COMMIT_MARKER_FILE,
                     write_manifest, verify_checkpoint, scan_tags,
                     find_latest_valid_checkpoint, quarantine_checkpoint,
                     prune_checkpoints, read_latest_tag, write_latest_tag)
from .universal import ds_to_universal, load_universal, load_universal_into
from .zero_to_fp32 import (get_fp32_state_dict_from_zero_checkpoint,
                           convert_zero_checkpoint_to_fp32_state_dict)
