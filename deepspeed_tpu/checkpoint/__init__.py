from .engine import CheckpointEngine, OrbaxCheckpointEngine
