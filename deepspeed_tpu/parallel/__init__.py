from .tp import tp_shardings, shard_params_for_tp, spec_from_logical, heuristic_spec, LOGICAL_RULES
