"""Tensor parallelism as sharding rules — the AutoTP analog.

Reference: ``module_inject/auto_tp.py:189`` parses the module graph and
row/col-shards linear weights, inserting explicit all-reduces
(``all_reduce_linears``). On TPU the same policy is expressed as *parameter
shardings over the ``model`` mesh axis*; XLA's SPMD partitioner propagates
activation shardings and inserts the psum the reference codes by hand.

Two sources of rules:
1. logical-axis metadata (flax ``nn.with_partitioning``) on model params —
   mapped via LOGICAL_RULES (the t5x-style rule table);
2. name heuristics for unannotated pytrees (the AutoTP fallback): column-
   parallel for q/k/v/gate/up/in-projections, row-parallel for o/down/out.
"""

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.mesh import MeshContext
from ..linear.quantization import QuantizedParameter
from ..utils.logging import logger

# logical axis name -> mesh axis (None = replicate); the t5x-style rule table
LOGICAL_RULES: List[Tuple[str, Optional[Any]]] = [
    ("embed", None),
    ("heads", "model"),
    ("kv", "model"),
    ("mlp", "model"),
    ("vocab", "model"),
    ("layers", None),
    ("expert", "expert"),
]


def spec_from_logical(names: Sequence[Optional[str]], rules=None) -> P:
    rules = dict(rules or LOGICAL_RULES)
    return P(*(rules.get(n) for n in names))


# AutoTP-style name heuristics (reference auto_tp.py partition policy)
_COL_PARALLEL = re.compile(r"(q_proj|k_proj|v_proj|gate_proj|up_proj|wi|fc1|c_fc|query|key|value)")
_ROW_PARALLEL = re.compile(r"(o_proj|down_proj|wo|fc2|c_proj|dense_4h_to_h|out_proj)")


def heuristic_spec(path: str, shape: Sequence[int], mp_size: int) -> P:
    """Column-parallel: shard output dim; row-parallel: shard input dim.
    Kernels are [in, out] in flax Dense."""
    if len(shape) < 2:
        return P()
    if _COL_PARALLEL.search(path) and shape[-1] % mp_size == 0:
        return P(*([None] * (len(shape) - 1) + ["model"]))
    if _ROW_PARALLEL.search(path) and shape[-2] % mp_size == 0:
        return P(*([None] * (len(shape) - 2) + ["model", None]))
    return P()


def lora_factor_specs(target: str, a_shape: Sequence[int],
                      b_shape: Sequence[int], mp_size: int) -> Tuple[P, P]:
    """PartitionSpecs for a stacked LoRA factor bank under TP — the AutoTP
    heuristics applied to the low-rank pair. A ``[n_slots, L, in, r]``
    contracts against the kernel's INPUT dim, B ``[n_slots, L, r, out]``
    produces its OUTPUT dim, so a column-parallel target (q/k/v/gate/up:
    kernel out-dim sharded) shards B's out-dim and replicates A (the rank-r
    intermediate stays tiny and replicated), while a row-parallel target
    (o/down: kernel in-dim sharded) shards A's in-dim alongside the sharded
    activations and replicates B — GSPMD then reduces the rank-r partials
    with the same psum it inserts for the base matmul. Non-divisible dims
    replicate, matching :func:`heuristic_spec`."""
    if mp_size <= 1:
        return P(), P()
    if _COL_PARALLEL.search(target) and b_shape[-1] % mp_size == 0:
        return P(), P(*([None] * (len(b_shape) - 1) + ["model"]))
    if _ROW_PARALLEL.search(target) and a_shape[-2] % mp_size == 0:
        return P(*([None] * (len(a_shape) - 2) + ["model", None])), P()
    return P(), P()


def woq_shard_dim(path: str, shape: Sequence[int], mp_size: int) -> Optional[int]:
    """Which dim of a kernel the AutoTP heuristics would shard over 'model'
    (None = replicated). The weight quantizer uses this to lay packed
    values/scales out shard-major so the quantized bytes split the same way
    the fp weights would."""
    spec = heuristic_spec(path, shape, mp_size)
    for i, ax in enumerate(spec):
        if ax == "model":
            return i
    return None


def path_str(path) -> str:
    """Public: jax key-path -> 'a/b/c' (shared by AutoTP + weight quantizer)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def tp_shardings(params: Any, ctx: MeshContext, logical_axes: Any = None,
                 rules=None) -> Any:
    """NamedSharding pytree for TP over the 'model' axis.

    QuantizedParameter leaves are handled as a unit: a shard-major qparam
    (shards > 1) gets ``P("model")`` on both its flat values and scales —
    each worker holds exactly its own contiguous segment — while a flat
    qparam replicates. The returned tree mirrors the params treedef (the
    sharding "leaf" for a qparam is a qparam of NamedShardings) so it feeds
    ``jax.device_put`` directly.
    """
    mp = ctx.mp_size

    if logical_axes is not None:
        return jax.tree_util.tree_map(
            lambda names: NamedSharding(ctx.mesh, spec_from_logical(names, rules))
            if names else NamedSharding(ctx.mesh, P()), logical_axes,
            is_leaf=lambda x: x is None or isinstance(x, tuple))

    def _one(path, leaf):
        if isinstance(leaf, QuantizedParameter):
            spec = P("model") if (leaf.shards > 1
                                  and leaf.shard_dim is not None) else P()
            ns = NamedSharding(ctx.mesh, spec)
            return QuantizedParameter(ns, ns, leaf.shape, leaf.block_size,
                                      leaf.dtype, leaf.q_bits, leaf.shard_dim,
                                      leaf.shards)
        return NamedSharding(ctx.mesh, heuristic_spec(path_str(path), leaf.shape, mp))

    return jax.tree_util.tree_map_with_path(
        _one, params, is_leaf=lambda x: isinstance(x, QuantizedParameter))


def shard_params_for_tp(params: Any, ctx: MeshContext, logical_axes: Any = None) -> Any:
    """Place params with TP shardings (inference path entry point)."""
    shardings = tp_shardings(params, ctx, logical_axes)
    return jax.device_put(params, shardings)


# ------------------------------------------------------------- TP wire dtype
#
# Gate ladder for the quantized TP collectives (mirrors the PR 4 kernel
# dispatch precedence): explicit config > DS_TPU_TP_WIRE env > default "fp".
# The wire is resolved per layer class so the final lm_head reduce can stay
# full-precision while attention/MLP outputs ride blockwise-int8.

TP_WIRE_CLASSES = ("attn_out", "mlp_out", "lm_head")
TP_WIRE_DTYPES = ("fp", "int8")


def resolve_tp_wire(config_value: Optional[str] = None,
                    overrides: Optional[Dict[str, str]] = None,
                    env: Optional[Dict[str, str]] = None
                    ) -> Tuple[Dict[str, str], str]:
    """Resolve the TP collective wire dtype per layer class.

    Returns ``(wire_map, source)`` where wire_map maps each of
    :data:`TP_WIRE_CLASSES` to ``"fp"`` or ``"int8"`` and source is one of
    ``config`` / ``env`` / ``default``. ``lm_head`` defaults to ``"fp"``
    even under a base of ``"int8"`` (logit-forming reduce keeps full
    precision) — an explicit per-class override can flip it.
    """
    env = os.environ if env is None else env
    if config_value:
        base, source = config_value, "config"
    elif env.get("DS_TPU_TP_WIRE"):
        base, source = env["DS_TPU_TP_WIRE"], "env"
    else:
        base, source = "fp", "default"
    if base not in TP_WIRE_DTYPES:
        raise ValueError(f"tp wire dtype must be one of {TP_WIRE_DTYPES}, "
                         f"got {base!r} (source: {source})")
    wire = {c: base for c in TP_WIRE_CLASSES}
    wire["lm_head"] = "fp"
    for cls, val in (overrides or {}).items():
        if cls not in TP_WIRE_CLASSES:
            raise ValueError(f"unknown tp wire class {cls!r}; "
                             f"expected one of {TP_WIRE_CLASSES}")
        if val not in TP_WIRE_DTYPES:
            raise ValueError(f"tp wire override {cls}={val!r} invalid; "
                             f"expected one of {TP_WIRE_DTYPES}")
        wire[cls] = val
    return wire, source
