"""Tensor parallelism as sharding rules — the AutoTP analog.

Reference: ``module_inject/auto_tp.py:189`` parses the module graph and
row/col-shards linear weights, inserting explicit all-reduces
(``all_reduce_linears``). On TPU the same policy is expressed as *parameter
shardings over the ``model`` mesh axis*; XLA's SPMD partitioner propagates
activation shardings and inserts the psum the reference codes by hand.

Two sources of rules:
1. logical-axis metadata (flax ``nn.with_partitioning``) on model params —
   mapped via LOGICAL_RULES (the t5x-style rule table);
2. name heuristics for unannotated pytrees (the AutoTP fallback): column-
   parallel for q/k/v/gate/up/in-projections, row-parallel for o/down/out.
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.mesh import MeshContext
from ..utils.logging import logger

# logical axis name -> mesh axis (None = replicate); the t5x-style rule table
LOGICAL_RULES: List[Tuple[str, Optional[Any]]] = [
    ("embed", None),
    ("heads", "model"),
    ("kv", "model"),
    ("mlp", "model"),
    ("vocab", "model"),
    ("layers", None),
    ("expert", "expert"),
]


def spec_from_logical(names: Sequence[Optional[str]], rules=None) -> P:
    rules = dict(rules or LOGICAL_RULES)
    return P(*(rules.get(n) for n in names))


# AutoTP-style name heuristics (reference auto_tp.py partition policy)
_COL_PARALLEL = re.compile(r"(q_proj|k_proj|v_proj|gate_proj|up_proj|wi|fc1|c_fc|query|key|value)")
_ROW_PARALLEL = re.compile(r"(o_proj|down_proj|wo|fc2|c_proj|dense_4h_to_h|out_proj)")


def heuristic_spec(path: str, shape: Sequence[int], mp_size: int) -> P:
    """Column-parallel: shard output dim; row-parallel: shard input dim.
    Kernels are [in, out] in flax Dense."""
    if len(shape) < 2:
        return P()
    if _COL_PARALLEL.search(path) and shape[-1] % mp_size == 0:
        return P(*([None] * (len(shape) - 1) + ["model"]))
    if _ROW_PARALLEL.search(path) and shape[-2] % mp_size == 0:
        return P(*([None] * (len(shape) - 2) + ["model", None]))
    return P()


def path_str(path) -> str:
    """Public: jax key-path -> 'a/b/c' (shared by AutoTP + weight quantizer)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def tp_shardings(params: Any, ctx: MeshContext, logical_axes: Any = None,
                 rules=None) -> Any:
    """NamedSharding pytree for TP over the 'model' axis."""
    mp = ctx.mp_size

    if logical_axes is not None:
        return jax.tree_util.tree_map(
            lambda names: NamedSharding(ctx.mesh, spec_from_logical(names, rules))
            if names else NamedSharding(ctx.mesh, P()), logical_axes,
            is_leaf=lambda x: x is None or isinstance(x, tuple))

    def _one(path, leaf):
        return NamedSharding(ctx.mesh, heuristic_spec(path_str(path), leaf.shape, mp))

    return jax.tree_util.tree_map_with_path(_one, params)


def shard_params_for_tp(params: Any, ctx: MeshContext, logical_axes: Any = None) -> Any:
    """Place params with TP shardings (inference path entry point)."""
    shardings = tp_shardings(params, ctx, logical_axes)
    return jax.device_put(params, shardings)
