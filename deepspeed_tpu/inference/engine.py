"""v1 inference engine.

Rebuild of reference ``deepspeed/inference/engine.py:41 InferenceEngine``:
wraps a model for serving — dtype cast, TP sharding over the ``model`` mesh
axis, compiled forward, and a ``generate`` loop. The reference's CUDA-graph
capture (:527) is subsumed by jit; kernel injection (:411) by XLA fusion +
Pallas kernels; TP groups (:257) by the mesh.

The ragged continuous-batching engine (FastGen, reference inference/v2) lives
in ``deepspeed_tpu/inference/v2``.
"""

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..comm.mesh import get_mesh_context, mesh_is_initialized
from .. import comm as dist
from ..utils.logging import logger
from .config import DeepSpeedInferenceConfig

try:
    import flax.linen as nn
    _HAS_FLAX = True
except ImportError:  # pragma: no cover
    _HAS_FLAX = False

from ..utils.dtypes import resolve_dtype


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None, params=None):
        self._config = config or DeepSpeedInferenceConfig()
        self.module = model
        self.dtype = resolve_dtype(self._config.dtype, jnp.bfloat16)

        if not mesh_is_initialized():
            tp = self._config.tensor_parallel.tp_size
            dist.init_distributed(mesh_axes={"model": tp, "data": -1} if tp > 1 else None)
        self.mesh_ctx = get_mesh_context()

        if _HAS_FLAX and isinstance(model, nn.Module):
            self._apply = lambda p, *a, **k: model.apply({"params": p}, *a, **k)
        elif callable(model):
            self._apply = model
        else:
            raise TypeError(f"model must be a flax Module or apply callable, got {type(model)}")

        self.params = None
        if params is not None:
            self.set_params(params)

        self._fwd = jax.jit(lambda p, a, k: self._apply(p, *a, **k))
        self._decode_step = jax.jit(self._decode_step_impl)

    def set_params(self, params):
        """Cast + (TP-)shard weights. With tp_size>1 the AutoTP analog in
        parallel/tp.py provides the sharding rules."""
        params = jax.tree_util.tree_map(
            lambda x: x.astype(self.dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        if self.mesh_ctx.mp_size > 1:
            from ..parallel.tp import shard_params_for_tp
            params = shard_params_for_tp(params, self.mesh_ctx)
        else:
            params = jax.device_put(params, self.mesh_ctx.replicated())
        self.params = params
        return self

    def forward(self, *args, **kwargs):
        """Compiled forward (reference :587)."""
        assert self.params is not None, "call set_params(params) before forward"
        return self._fwd(self.params, args, kwargs)

    __call__ = forward

    def _decode_step_impl(self, params, buf, cur, rng, finished, temperature, eos):
        """One decode step over a FIXED-length buffer: the jit signature never
        changes across tokens (a growing ids array would recompile the model
        per token). Causal attention makes the garbage beyond `cur` inert."""
        logits = self._apply(params, buf)
        next_logits = logits[:, cur - 1, :]
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(sub, next_logits / jnp.maximum(temperature, 1e-6), axis=-1)
        greedy = jnp.argmax(next_logits, axis=-1)
        nxt = jnp.where(temperature > 0, sampled, greedy)
        nxt = jnp.where(finished, eos, nxt)
        finished = finished | (nxt == eos)
        buf = buf.at[:, cur].set(nxt.astype(buf.dtype))
        return buf, cur + 1, rng, finished

    def generate(self, input_ids, max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, rng: Optional[jax.Array] = None):
        """Greedy/temperature decode over a fixed-size buffer (one compile).
        This v1 path recomputes the prefix each token (no KV cache) — correct
        but O(n^2) FLOPs; the v2 ragged engine holds the paged KV cache
        (reference inference/v2)."""
        assert self.params is not None
        ids = jnp.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s0 = ids.shape
        if rng is None:
            rng = jax.random.PRNGKey(0)
        buf = jnp.pad(ids, ((0, 0), (0, max_new_tokens)))
        cur = jnp.int32(s0)
        finished = jnp.zeros((b, ), dtype=bool)
        temp = jnp.float32(temperature)
        # eos=-1 sentinel never matches a real token -> no early finish
        eos = jnp.int32(eos_token_id if eos_token_id is not None else -1)
        for i in range(max_new_tokens):
            buf, cur, rng, finished = self._decode_step(self.params, buf, cur, rng, finished,
                                                        temp, eos)
            # host sync for early exit only when an eos is in play
            if eos_token_id is not None and bool(finished.all()):
                return buf[:, :s0 + i + 1]
        return buf

    def profile_model_time(self, use_cuda_events=True):
        logger.warning("profile_model_time: use jax.profiler traces on TPU")
