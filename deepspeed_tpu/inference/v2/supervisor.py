"""Serving supervisor: relaunch a crashed serving daemon under a budget.

The durable-serving story (``journal.py`` + warm-restart replay in
``server.py``) makes a daemon crash *recoverable*; this module makes it
*recovered* — a host-side supervisor in the ``elasticity/agent.py``
DSElasticAgent shape wraps the daemon process, and when the daemon exits
nonzero it relaunches it with exponential backoff until a restart budget
is exhausted. The relaunched daemon finds the write-ahead journal on
boot, re-admits every unfinished request, and continues each stream
byte-identically; clients re-attach over HTTP with
``GET /requests/<uid>/stream?from_token=N``.

What the supervisor exports to each child generation:

* ``DS_SERVE_RESTART_COUNT`` — how many relaunches preceded this one;
  surfaces in ``/health`` / ``stats()`` as ``restart_count``.
* ``DS_SERVE_RESTART_BUDGET_REMAINING`` — restarts left in the budget;
  surfaces in ``/health`` as ``restart_budget_remaining`` so the fleet
  router can prefer replicas with headroom.
* the caller's env otherwise verbatim, so ``DS_TPU_JOURNAL_DIR`` (and
  everything else) flows through — successive generations share one
  journal directory by construction.

The restart budget *heals*: after ``budget_reset_after_s`` of healthy
child uptime the restart counter returns to zero. Without this, a
long-lived daemon spends its lifetime budget on unrelated crashes days
apart and the Nth transient fault becomes terminal. Relaunch backoff is
full-jittered (``utils/retry.backoff_delay``) so a rack of supervisors
recovering from one power event doesn't relaunch in lockstep.

Readiness is gated on the daemon's own ``/health`` endpoint: after each
launch the supervisor polls ``health_url`` until HTTP 200 (a 503 means
the server is up but degraded — still "arrived", the watchdog owns it
from there). A child that dies before becoming ready consumes a restart
from the same budget as a mid-flight crash.
"""

import os
import random
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from ...observability import get_registry, get_tracer
from ...utils.logging import logger
from ...utils.retry import backoff_delay

# Restart accounting (process registry, resolved at import). The restart
# histogram measures death-detected → child-ready (or ready-timeout) — the
# real unavailability window a client sees across a warm restart.
_obs = get_registry()
_restarts_total = _obs.counter(
    "ds_supervisor_restarts_total", "Daemon warm restarts (crash relaunches)")
_restart_seconds = _obs.histogram(
    "ds_supervisor_restart_seconds",
    "Warm restart wall time: crash detected to child ready",
    lo=1e-3, hi=1e4, buckets_per_decade=10)


def _wait_ready(health_url: str, timeout_s: float,
                proc: Optional[subprocess.Popen] = None,
                poll_s: float = 0.25) -> bool:
    """Poll ``health_url`` until any HTTP response arrives (200 ready, 503
    degraded — both mean the server is up) or ``timeout_s`` elapses.
    Connection refused / reset means the socket isn't listening yet — keep
    polling. Returns False early if ``proc`` exits while we wait."""
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return False
        try:
            with urllib.request.urlopen(health_url, timeout=2.0):
                return True
        except urllib.error.HTTPError:
            return True  # 503 et al: the server answered — it's alive
        except (urllib.error.URLError, OSError, TimeoutError):
            pass
        time.sleep(poll_s)
    return False


class ServingSupervisor:
    """Supervise one serving daemon process with budgeted warm restarts.

    ``run()`` blocks until the daemon exits cleanly (returns 0), the
    restart budget is exhausted (returns the last exit code), or the
    supervisor itself is interrupted (child is torn down SIGTERM → grace
    → SIGKILL)."""

    def __init__(self, cmd: Sequence[str],
                 max_restarts: int = 3,
                 monitor_interval: float = 0.5,
                 restart_backoff: float = 0.5,
                 max_backoff: float = 30.0,
                 health_url: Optional[str] = None,
                 ready_timeout_s: float = 120.0,
                 grace_s: float = 30.0,
                 env: Optional[dict] = None,
                 budget_reset_after_s: float = 600.0,
                 backoff_jitter: str = "full",
                 jitter_seed: Optional[int] = None):
        self.cmd = list(cmd)
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.restart_backoff = float(restart_backoff)
        self.max_backoff = float(max_backoff)
        self.health_url = health_url
        self.ready_timeout_s = float(ready_timeout_s)
        self.grace_s = float(grace_s)
        self.base_env = dict(env if env is not None else os.environ)
        self.budget_reset_after_s = float(budget_reset_after_s)
        self.backoff_jitter = backoff_jitter
        self._rng = (random.Random(jitter_seed)
                     if jitter_seed is not None else None)
        self.restarts = 0
        self.history: List[dict] = []

    @property
    def budget_remaining(self) -> int:
        return max(0, self.max_restarts - self.restarts)

    # ------------------------------------------------------------------

    def _launch(self) -> subprocess.Popen:
        env = dict(self.base_env)
        env["DS_SERVE_RESTART_COUNT"] = str(self.restarts)
        env["DS_SERVE_RESTART_BUDGET_REMAINING"] = str(self.budget_remaining)
        self.history.append({"restart": self.restarts, "t": time.time()})
        logger.info(f"ServingSupervisor: launching daemon "
                    f"(restart {self.restarts}/{self.max_restarts})")
        return subprocess.Popen(self.cmd, env=env)

    def _terminate(self, proc: subprocess.Popen) -> None:
        """SIGTERM (the daemon's handoff path: drain + journal checkpoint),
        wait out the grace period, then SIGKILL."""
        if proc.poll() is not None:
            return
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=self.grace_s)
        except subprocess.TimeoutExpired:
            logger.warning("ServingSupervisor: daemon ignored SIGTERM "
                           f"for {self.grace_s}s — killing")
            proc.kill()
            proc.wait()

    def _await_ready(self, proc: subprocess.Popen) -> None:
        if self.health_url is None:
            return
        if _wait_ready(self.health_url, self.ready_timeout_s, proc=proc):
            logger.info(f"ServingSupervisor: daemon ready "
                        f"({self.health_url})")
        elif proc.poll() is None:
            # still running but unreachable — let the poll loop decide;
            # a wedged-at-boot daemon will be caught by its own watchdog
            # or by the operator, not silently killed here
            logger.warning(
                f"ServingSupervisor: daemon not ready after "
                f"{self.ready_timeout_s}s ({self.health_url})")

    def run(self) -> int:
        proc = self._launch()
        t_launched = time.monotonic()
        self._await_ready(proc)
        try:
            while True:
                rc = proc.poll()
                if rc is None:
                    time.sleep(self.monitor_interval)
                    continue
                if rc == 0:
                    logger.info("ServingSupervisor: clean exit")
                    return 0
                t_down = time.monotonic()
                uptime = t_down - t_launched
                if (self.restarts > 0 and self.budget_reset_after_s > 0
                        and uptime >= self.budget_reset_after_s):
                    # a healthy-uptime window proves the last restart
                    # worked — forget old crashes so the budget measures
                    # crash *loops*, not lifetime totals
                    logger.info(
                        f"ServingSupervisor: {uptime:.0f}s healthy uptime "
                        f"— restart budget reset ({self.restarts} forgiven)")
                    self.restarts = 0
                self.restarts += 1
                _restarts_total.inc()
                if self.restarts > self.max_restarts:
                    logger.error(
                        f"ServingSupervisor: restart budget exhausted "
                        f"({self.max_restarts}); last rc={rc}")
                    return rc
                backoff = backoff_delay(self.restarts - 1,
                                        base_delay=self.restart_backoff,
                                        max_delay=self.max_backoff,
                                        jitter=self.backoff_jitter,
                                        rng=self._rng)
                logger.warning(
                    f"ServingSupervisor: daemon died rc={rc} — warm restart "
                    f"{self.restarts}/{self.max_restarts} in {backoff:.2f}s")
                if backoff > 0:
                    time.sleep(backoff)
                proc = self._launch()
                t_launched = time.monotonic()
                self._await_ready(proc)
                t_up = time.monotonic()
                _restart_seconds.record(t_up - t_down)
                get_tracer().global_span(
                    "supervisor_restart", t_down, t_up,
                    args={"rc": rc, "restart": self.restarts,
                          "backoff_s": round(backoff, 3)})
        finally:
            if proc.poll() is None:
                self._terminate(proc)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="Serving daemon supervisor (warm restart + journal replay)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--monitor-interval", type=float, default=0.5)
    ap.add_argument("--restart-backoff", type=float, default=0.5)
    ap.add_argument("--health-url", default=None,
                    help="e.g. http://127.0.0.1:8100/health — gate readiness "
                         "on the daemon's own health endpoint")
    ap.add_argument("--ready-timeout", type=float, default=120.0)
    ap.add_argument("--grace", type=float, default=30.0,
                    help="seconds between SIGTERM and SIGKILL on teardown")
    ap.add_argument("--budget-reset-after", type=float, default=600.0,
                    help="healthy-uptime seconds after which the restart "
                         "budget resets (0 disables)")
    ap.add_argument("--backoff-jitter", choices=("none", "full"),
                    default="full",
                    help="relaunch backoff jitter policy")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="serving command (after --)")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":  # only the LEADING separator; the child may
        cmd = cmd[1:]           # legitimately use "--" in its own argv
    if not cmd:
        ap.error("no serving command given")
    sup = ServingSupervisor(
        cmd,
        max_restarts=args.max_restarts,
        monitor_interval=args.monitor_interval,
        restart_backoff=args.restart_backoff,
        health_url=args.health_url,
        ready_timeout_s=args.ready_timeout,
        grace_s=args.grace,
        budget_reset_after_s=args.budget_reset_after,
        backoff_jitter=args.backoff_jitter)
    sys.exit(sup.run())


if __name__ == "__main__":
    main()
