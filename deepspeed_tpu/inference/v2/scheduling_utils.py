"""Scheduling result codes (reference ``inference/v2/scheduling_utils.py``)."""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class SchedulingError(RuntimeError):

    def __init__(self, result: SchedulingResult):
        self.status = result
        super().__init__(f"Scheduling failed: {result}")


class DeadlineExceeded(RuntimeError):
    """A request ran past its ``deadline_s`` / ``queue_ttl_s``: the
    scheduler error-finishes it and releases its KV reservation. The HTTP
    front end maps this to 504."""


class SchedulerOverloaded(RuntimeError):
    """Admission refused by the load-shed policy: the queue sits at
    ``max_queued`` / ``max_queued_tokens``. The HTTP front end maps this
    to 429 with a ``Retry-After`` header of ``retry_after_s``."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        self.retry_after_s = float(retry_after_s)
        super().__init__(msg)


class UnsupportedFeature(ValueError):
    """A request or config names a feature this build rejects (KV offload,
    unsupported chat-completion knobs, ...). Subclasses ValueError so
    existing ``except ValueError`` rejection paths keep working, but
    carries a machine-readable ``reason`` slug the HTTP front end surfaces
    in the 400 body — clients branch on the slug, not on message text."""

    def __init__(self, msg: str, reason: str):
        self.reason = str(reason)
        super().__init__(msg)


def error_reason(exc: BaseException):
    """Best-effort machine-readable reason slug for a rejection: the
    ``reason`` attribute of :class:`UnsupportedFeature`, or the custom
    error type a pydantic ValidationError carries (config validators use
    ``PydanticCustomError`` slugs — pydantic wraps any ValueError raised
    inside a validator, so the slug is how the type survives the wrap).
    Returns None when the error has no structured reason."""
    r = getattr(exc, "reason", None)
    if isinstance(r, str) and r:
        return r
    errors = getattr(exc, "errors", None)  # pydantic ValidationError
    if callable(errors):
        try:
            for e in errors():
                t = e.get("type")
                if isinstance(t, str) and t not in (
                        "value_error", "assertion_error"):
                    return t
        except Exception:  # noqa: BLE001 — reporting is best-effort
            return None
    return None
