"""Scheduling result codes (reference ``inference/v2/scheduling_utils.py``)."""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class SchedulingError(RuntimeError):

    def __init__(self, result: SchedulingResult):
        self.status = result
        super().__init__(f"Scheduling failed: {result}")


class DeadlineExceeded(RuntimeError):
    """A request ran past its ``deadline_s`` / ``queue_ttl_s``: the
    scheduler error-finishes it and releases its KV reservation. The HTTP
    front end maps this to 504."""


class SchedulerOverloaded(RuntimeError):
    """Admission refused by the load-shed policy: the queue sits at
    ``max_queued`` / ``max_queued_tokens``. The HTTP front end maps this
    to 429 with a ``Retry-After`` header of ``retry_after_s``."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        self.retry_after_s = float(retry_after_s)
        super().__init__(msg)
