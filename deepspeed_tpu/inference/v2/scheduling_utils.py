"""Scheduling result codes (reference ``inference/v2/scheduling_utils.py``)."""

from enum import Enum


class SchedulingResult(Enum):
    Success = 0
    EngineSequenceLimitExceeded = 1
    BatchSequenceLimitExceeded = 2
    BatchTokenLimitExceeded = 3
    KVCacheLimitExceeded = 4
    SequenceTokenLimitExceeded = 5


class SchedulingError(RuntimeError):

    def __init__(self, result: SchedulingResult):
        self.status = result
        super().__init__(f"Scheduling failed: {result}")
