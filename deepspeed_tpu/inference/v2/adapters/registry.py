"""Multi-LoRA adapter registry for the v2 serving engine.

Beyond the reference (which carries LoRA plumbing for TRAINING in
``linear/optimized_linear.py`` and ``module_inject``): this is the serving
side — N adapters ride ONE compiled program instead of N recompiles or N
replicas.

Design:

- Adapters are loaded from checkpoint dirs (``adapter_config.json`` +
  ``weights.npz``) and validated through the SAME ``linear.config.LoRAConfig``
  dataclass the training path uses — one spec, one ``alpha / sqrt(r)``
  scaling rule.
- Device residency is a fixed pool of SLOTS: stacked factor banks
  ``A [n_slots, L, in, r_pad]`` / ``B [n_slots, L, r_pad, out]`` per target
  kernel plus a ``scale [n_slots]`` vector. Slot 0 is the identity adapter
  (zero factors, zero scale): base-only rows compute an exactly-zero delta,
  so their streams stay bit-identical to the adapter-free engine.
- The bank is a TRACED operand of every fused program: its shapes are fixed
  by ``max_live_adapters``/``slot_rank_pad`` at construction, so loading,
  evicting, or hot-swapping adapters only changes VALUES — one jitted
  donated ``bank.at[slot].set(...)`` per factor, no recompile, no restart.
- Residency is LRU over UNPINNED slots: every in-flight request pins its
  adapter's slot (``pin``/``unpin`` keyed by uid), so a live stream's
  factors can never be evicted mid-decode.
- Ids are VERSIONED (``name@version``): reloading a name bumps the version,
  and the serving journal records the resolved versioned id, so durable
  replay and WAL fleet migration re-resolve the exact factors the original
  stream decoded with (or fail loudly — never a silent base fallback).
"""

import json
import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ....linear.config import LoRAConfig
from ....observability import get_registry as _get_obs

_obs = _get_obs()
_loads_total = _obs.counter(
    "ds_adapter_loads_total",
    "Adapters loaded into the registry (boot scan + POST /adapters/load)")
_evictions_total = _obs.counter(
    "ds_adapter_evictions_total",
    "LRU evictions of device-resident adapter slots")
_live_gauge = _obs.gauge(
    "ds_adapter_live",
    "Adapters currently device-resident (occupied slots, identity excluded)")


class AdapterSlotsExhausted(RuntimeError):
    """Every device slot is pinned by an in-flight request — the load/pin
    must wait for streams to finish (HTTP maps this to 429 + Retry-After,
    like scheduler overload)."""


def _target_dims(model_config, target: str) -> Tuple[int, int]:
    """(in_dim, out_dim) of one projection kernel under the model config."""
    cfg = model_config
    hd = cfg.head_dim_
    H = cfg.hidden_size
    dims = {
        "q_proj": (H, cfg.num_attention_heads * hd),
        "k_proj": (H, cfg.num_key_value_heads * hd),
        "v_proj": (H, cfg.num_key_value_heads * hd),
        "o_proj": (cfg.num_attention_heads * hd, H),
        "gate_proj": (H, cfg.intermediate_size),
        "up_proj": (H, cfg.intermediate_size),
        "down_proj": (cfg.intermediate_size, H),
    }
    return dims[target]


def save_adapter(path: str, spec: LoRAConfig, factors: Dict[str, tuple],
                 name: Optional[str] = None,
                 version: Optional[int] = None) -> str:
    """Write one adapter checkpoint dir (the registry's load format):
    ``adapter_config.json`` (the LoRAConfig fields) + ``weights.npz`` with
    ``{target}.lora_a`` ``[L, in, r]`` / ``{target}.lora_b`` ``[L, r, out]``
    stacked over layers. Returns ``path``. The writer for tests, benches,
    and training-side export."""
    os.makedirs(path, exist_ok=True)
    spec.validate()
    cfg = {"lora_r": int(spec.lora_r), "lora_alpha": float(spec.lora_alpha),
           "lora_dtype": spec.lora_dtype, "targets": list(spec.targets)}
    if name is not None:
        cfg["name"] = name
    if version is not None:
        cfg["version"] = int(version)
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump(cfg, f, indent=1)
    arrs = {}
    for t, (a, b) in factors.items():
        arrs[f"{t}.lora_a"] = np.asarray(a)
        arrs[f"{t}.lora_b"] = np.asarray(b)
    np.savez(os.path.join(path, "weights.npz"), **arrs)
    return path


class _Record:
    """One loaded adapter version: host-resident padded factors + spec."""

    __slots__ = ("adapter_id", "name", "version", "spec", "factors", "scale")

    def __init__(self, adapter_id, name, version, spec, factors, scale):
        self.adapter_id = adapter_id
        self.name = name
        self.version = version
        self.spec = spec
        self.factors = factors  # target -> (a [L, in, r_pad], b [L, r_pad, out])
        self.scale = scale


class AdapterRegistry:
    """Load/validate/pin/unpin LoRA adapters and keep an LRU of
    device-resident slots backing the fused programs' stacked factor bank.

    Thread-safe: the scheduler's submit path (pin), finish path (unpin),
    and the admin endpoints (load/unload) run on different threads.
    """

    def __init__(self, config, model):
        self._config = config
        self._model = model
        mcfg = model.config
        self._L = int(mcfg.num_hidden_layers)
        self._r_pad = int(config.slot_rank_pad)
        self._n_slots = int(config.max_live_adapters) + 1  # + identity slot 0
        self._targets = tuple(config.targets)
        moe_mlp = ({"gate_proj", "up_proj", "down_proj"} & set(self._targets)
                   if getattr(mcfg, "num_local_experts", 0) else set())
        if moe_mlp:
            # the LoRA hooks only ride the DENSE MLP path; silently serving
            # a config that never applies its MLP deltas would be a wrong
            # answer, not a degraded one
            raise ValueError(
                f"adapters.targets {sorted(moe_mlp)} are MLP projections but "
                "the model is MoE (num_local_experts > 0) — expert MLPs have "
                "no LoRA hook; restrict targets to attention projections")
        self._lock = threading.RLock()
        self._records: Dict[str, _Record] = {}   # adapter_id -> record
        self._latest: Dict[str, str] = {}        # name -> latest adapter_id
        self._versions: Dict[str, int] = {}      # name -> last version number
        self._slot_of: Dict[str, int] = {}       # adapter_id -> live slot
        self._id_at: Dict[int, str] = {}         # slot -> adapter_id
        self._pins: Dict[int, int] = {}          # slot -> pin count
        self._uid_slot: Dict[int, int] = {}      # uid -> pinned slot
        self._uid_id: Dict[int, str] = {}        # uid -> adapter_id
        self._clock = 0                          # LRU timestamps
        self._last_used: Dict[int, int] = {}     # slot -> clock
        self._loads = 0
        self._evictions = 0

        import jax
        import jax.numpy as jnp
        dtype = model.dtype
        mesh_ctx = getattr(model, "_mesh_ctx", None)
        factors = {}
        self._writers = {}

        def _writer(sharding=None):
            kw = {"out_shardings": sharding} if sharding is not None else {}
            return jax.jit(lambda leaf, val, slot: leaf.at[slot].set(val),
                           donate_argnums=(0,), **kw)

        for t in self._targets:
            di, do = _target_dims(mcfg, t)
            a = jnp.zeros((self._n_slots, self._L, di, self._r_pad), dtype)
            b = jnp.zeros((self._n_slots, self._L, self._r_pad, do), dtype)
            sh_a = sh_b = None
            if mesh_ctx is not None:
                # TP: factor shards follow the base kernel's AutoTP dims
                # (parallel/tp.lora_factor_specs) so the grouped delta's
                # activations line up with the sharded base matmul
                from jax.sharding import NamedSharding
                from ....parallel.tp import lora_factor_specs
                spec_a, spec_b = lora_factor_specs(
                    t, a.shape, b.shape, model.tp_size)
                sh_a = NamedSharding(mesh_ctx.mesh, spec_a)
                sh_b = NamedSharding(mesh_ctx.mesh, spec_b)
                a = jax.device_put(a, sh_a)
                b = jax.device_put(b, sh_b)
            factors[t] = (a, b)
            self._writers[(t, "a")] = _writer(sh_a)
            self._writers[(t, "b")] = _writer(sh_b)
        scale = jnp.zeros((self._n_slots,), jnp.float32)
        sh_s = None
        if mesh_ctx is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh_s = NamedSharding(mesh_ctx.mesh, P())
            scale = jax.device_put(scale, sh_s)
        self._writers["scale"] = _writer(sh_s)
        self.bank = {"factors": factors, "scale": scale}
        # warm the slot-write programs against the identity slot (writing
        # zeros to slot 0 is a no-op by value), so the first live
        # POST /adapters/load compiles nothing
        self._device_write(0, {}, 0.0)
        if config.registry_dir:
            self.scan_dir(config.registry_dir)

    # ---- loading / unloading ----

    @property
    def n_slots(self) -> int:
        return self._n_slots

    @property
    def rank_pad(self) -> int:
        return self._r_pad

    @property
    def targets(self) -> Tuple[str, ...]:
        return self._targets

    def scan_dir(self, root: str) -> list:
        """Boot scan: each subdirectory with an ``adapter_config.json`` is
        one adapter (name defaults to the subdirectory name). Bad entries
        log and skip — one broken checkpoint must not kill the daemon."""
        from ....utils.logging import logger
        loaded = []
        if not os.path.isdir(root):
            return loaded
        for entry in sorted(os.listdir(root)):
            d = os.path.join(root, entry)
            if not os.path.isfile(os.path.join(d, "adapter_config.json")):
                continue
            try:
                loaded.append(self.load(d, name=entry))
            except Exception as e:  # noqa: BLE001 — boot must survive
                logger.warning(f"adapter scan: skipping {d}: {e}")
        return loaded

    def load(self, path: str, name: Optional[str] = None) -> str:
        """Load + validate one adapter checkpoint dir; returns the
        VERSIONED adapter id (``name@version``). Validation failures raise
        ValueError with an actionable message (the HTTP layer maps them to
        structured 400s). Loading an explicit (name, version) pair that is
        already registered is idempotent."""
        cfg_path = os.path.join(path, "adapter_config.json")
        if not os.path.isfile(cfg_path):
            raise ValueError(f"no adapter_config.json under {path!r}")
        with open(cfg_path) as f:
            raw = json.load(f)
        name = name or raw.get("name") or os.path.basename(
            os.path.normpath(path))
        spec = LoRAConfig(
            lora_r=int(raw.get("lora_r", 0) or 0),
            lora_alpha=float(raw.get("lora_alpha", 0.0)),
            lora_dtype=raw.get("lora_dtype", "bfloat16"),
            targets=tuple(raw.get("targets") or ()))
        if spec.lora_r > self._r_pad:
            raise ValueError(
                f"adapter {name!r}: lora_r={spec.lora_r} exceeds the bank's "
                f"slot_rank_pad={self._r_pad} — raise adapters.slot_rank_pad")
        extra = set(spec.targets) - set(self._targets)
        if extra:
            raise ValueError(
                f"adapter {name!r} targets {sorted(extra)} outside the "
                f"configured bank targets {list(self._targets)} — serving it "
                f"would silently drop trained factors")
        wpath = os.path.join(path, "weights.npz")
        if not os.path.isfile(wpath):
            raise ValueError(f"no weights.npz under {path!r}")
        factors = {}
        with np.load(wpath) as z:
            for t in spec.targets:
                ka, kb = f"{t}.lora_a", f"{t}.lora_b"
                if ka not in z.files or kb not in z.files:
                    raise ValueError(
                        f"adapter {name!r}: weights.npz missing {ka}/{kb}")
                a, b = np.asarray(z[ka]), np.asarray(z[kb])
                di, do = _target_dims(self._model.config, t)
                r = spec.lora_r
                if a.shape != (self._L, di, r) or b.shape != (self._L, r, do):
                    raise ValueError(
                        f"adapter {name!r} target {t}: factor shapes "
                        f"{a.shape}/{b.shape} do not match model dims "
                        f"[{self._L}, {di}, {r}] / [{self._L}, {r}, {do}]")
                pa = np.zeros((self._L, di, self._r_pad), np.float32)
                pb = np.zeros((self._L, self._r_pad, do), np.float32)
                pa[:, :, :r] = a  # zero rank padding is mathematically inert
                pb[:, :r, :] = b
                factors[t] = (pa, pb)
        with self._lock:
            want = raw.get("version")
            if want is not None:
                aid = f"{name}@{int(want)}"
                if aid in self._records:
                    return aid  # idempotent re-load of a pinned version
                version = int(want)
                self._versions[name] = max(self._versions.get(name, 0),
                                           version)
            else:
                version = self._versions.get(name, 0) + 1
                self._versions[name] = version
                aid = f"{name}@{version}"
            self._records[aid] = _Record(aid, name, version, spec, factors,
                                         spec.scaling)
            self._latest[name] = aid
            self._loads += 1
        _loads_total.inc()
        return aid

    def unload(self, name_or_id: str) -> str:
        """Drop one adapter version from the registry (and its device slot,
        when resident). Refuses while in-flight requests pin it — a live
        stream's factors never vanish out from under it."""
        with self._lock:
            aid = self.resolve(name_or_id)
            slot = self._slot_of.get(aid)
            if slot is not None and self._pins.get(slot, 0) > 0:
                raise ValueError(
                    f"adapter {aid!r} is pinned by "
                    f"{self._pins[slot]} in-flight request(s)")
            if slot is not None:
                self._release_slot(slot)
            rec = self._records.pop(aid)
            if self._latest.get(rec.name) == aid:
                prev = [r for r in self._records.values()
                        if r.name == rec.name]
                if prev:
                    self._latest[rec.name] = max(
                        prev, key=lambda r: r.version).adapter_id
                else:
                    del self._latest[rec.name]
            return aid

    def resolve(self, name_or_id: str) -> str:
        """Resolve a user-facing name (latest version) or an exact
        ``name@version`` id to the versioned id. KeyError when unknown —
        the submit path maps this to a structured 400, never a silent
        base-weight fallback."""
        with self._lock:
            if name_or_id in self._records:
                return name_or_id
            aid = self._latest.get(name_or_id)
            if aid is None:
                raise KeyError(f"unknown adapter {name_or_id!r}")
            return aid

    # ---- device residency (slots) ----

    def _release_slot(self, slot: int) -> None:
        aid = self._id_at.pop(slot, None)
        if aid is not None:
            self._slot_of.pop(aid, None)
        self._pins.pop(slot, None)
        self._last_used.pop(slot, None)
        # hygiene: a freed slot's scale drops to 0 so even a stale slot
        # index (a bug) yields a zero delta, not another tenant's adapter
        self._device_write(slot, None, 0.0)
        _live_gauge.set(len(self._id_at))

    def _device_write(self, slot: int, factors, scale: float) -> None:
        """Write one slot of the stacked bank in place (jitted donated
        updates — slot index traced, so every hot swap reuses the same
        compiled programs). ``factors=None`` writes only the scale;
        ``factors={}`` zero-fills every target (the identity write)."""
        import jax.numpy as jnp
        bank = self.bank
        dtype = self._model.dtype
        new_factors = dict(bank["factors"])
        if factors is not None:
            for t, (a, b) in bank["factors"].items():
                fa, fb = factors.get(t, (None, None))
                va = (jnp.asarray(fa, dtype) if fa is not None
                      else jnp.zeros(a.shape[1:], dtype))
                vb = (jnp.asarray(fb, dtype) if fb is not None
                      else jnp.zeros(b.shape[1:], dtype))
                a = self._writers[(t, "a")](a, va, jnp.int32(slot))
                b = self._writers[(t, "b")](b, vb, jnp.int32(slot))
                new_factors[t] = (a, b)
        new_scale = self._writers["scale"](
            bank["scale"], jnp.float32(scale), jnp.int32(slot))
        self.bank = {"factors": new_factors, "scale": new_scale}

    def _acquire_slot(self, aid: str) -> int:
        """Make ``aid`` device-resident and return its slot (caller holds
        the lock). Prefers a free slot; else LRU-evicts an unpinned one;
        raises :class:`AdapterSlotsExhausted` when every slot is pinned."""
        slot = self._slot_of.get(aid)
        if slot is not None:
            return slot
        free = [s for s in range(1, self._n_slots) if s not in self._id_at]
        if free:
            slot = free[0]
        else:
            unpinned = [s for s in self._id_at
                        if self._pins.get(s, 0) == 0]
            if not unpinned:
                raise AdapterSlotsExhausted(
                    f"all {self._n_slots - 1} adapter slots are pinned by "
                    "in-flight requests")
            slot = min(unpinned, key=lambda s: self._last_used.get(s, 0))
            evicted = self._id_at.pop(slot)
            self._slot_of.pop(evicted, None)
            self._evictions += 1
            _evictions_total.inc()
        rec = self._records[aid]
        self._device_write(slot, rec.factors, rec.scale)
        self._slot_of[aid] = slot
        self._id_at[slot] = aid
        _live_gauge.set(len(self._id_at))
        return slot

    def pin(self, uid: int, name_or_id: str) -> int:
        """Resolve + pin one request's adapter for its lifetime; returns
        the device slot its rows carry. Raises KeyError (unknown id) or
        AdapterSlotsExhausted (every slot pinned)."""
        with self._lock:
            aid = self.resolve(name_or_id)
            if uid in self._uid_slot:
                if self._uid_id.get(uid) == aid:
                    return self._uid_slot[uid]
                self._unpin_locked(uid)
            slot = self._acquire_slot(aid)
            self._pins[slot] = self._pins.get(slot, 0) + 1
            self._clock += 1
            self._last_used[slot] = self._clock
            self._uid_slot[uid] = slot
            self._uid_id[uid] = aid
            return slot

    def _unpin_locked(self, uid: int) -> None:
        slot = self._uid_slot.pop(uid, None)
        self._uid_id.pop(uid, None)
        if slot is not None and slot in self._pins:
            self._pins[slot] = max(0, self._pins[slot] - 1)

    def unpin(self, uid: int) -> None:
        """Release a finished request's pin (no-op for unknown uids, so
        every finish path can call it unconditionally)."""
        with self._lock:
            self._unpin_locked(uid)

    def slot_for_uid(self, uid: int) -> int:
        """The slot a pinned request's rows decode with (0 = identity)."""
        with self._lock:
            return self._uid_slot.get(uid, 0)

    def adapter_for_uid(self, uid: int) -> Optional[str]:
        with self._lock:
            return self._uid_id.get(uid)

    # ---- reporting ----

    def stats(self) -> dict:
        with self._lock:
            return {
                "registered": sorted(self._records),
                "live": {aid: s for aid, s in sorted(self._slot_of.items())},
                "pinned": {self._id_at[s]: n for s, n in self._pins.items()
                           if n > 0 and s in self._id_at},
                "max_live_adapters": self._n_slots - 1,
                "slot_rank_pad": self._r_pad,
                "targets": list(self._targets),
                "registry_dir": self._config.registry_dir,
                "loads": self._loads,
                "evictions": self._evictions,
            }
