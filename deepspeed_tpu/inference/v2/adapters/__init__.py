from .registry import (AdapterRegistry, AdapterSlotsExhausted, save_adapter)

__all__ = ["AdapterRegistry", "AdapterSlotsExhausted", "save_adapter"]
