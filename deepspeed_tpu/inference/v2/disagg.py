"""Disaggregated prefill/decode serving: split device groups with an
overlapped KV-page handoff.

The continuous-fusion scheduler (PR 10) overlaps prefill and decode in
TIME on one device group — but a long prompt still steals token budget
and device cycles from the fused K-step wave, inflating decode
inter-token p99. This module extends the overlap into SPACE:

* the local device set is carved into a PREFILL group and a DECODE group
  (``disaggregation`` config block: ``prefill_fraction`` or explicit
  device lists; per-group TP reuses the PR 12 sharding on a *private*
  mesh, so both groups' engines coexist in one process);
* the server scheduler routes ``pending > 1`` requests to the prefill
  group, which runs chunked prefill concurrently with the decode group's
  fused wave;
* completed prefix KV pages migrate through :class:`HandoffQueue` — a
  double-buffered async ``jax.device_put`` mover. Each transfer batch is
  LAYER-BATCHED by construction: the paged pool is one
  ``[2L, slots, KV*D]`` array with a block's slots contiguous, so one
  slice per block carries every layer's K and V at once. The transfer of
  chunk N overlaps prefill of chunk N+1 (submission is async; at the
  in-flight cap the *submitter* blocks, never the decode group), and
  pages land in the decode pool via a jitted donated
  ``dynamic_update_slice`` at block granularity — the landed blocks then
  enter the decode engine's descriptor/prefix-cache accounting exactly
  like locally computed prefill (``InferenceEngineV2.adopt_handoff``).

Invariants:

* **Bit-identical streams.** Routing changes WHERE the same compiled
  programs run, never the per-sequence PRNG key chains (tracked as
  ``key_burns`` on the request, engine-independent) or the values they
  produce — greedy, sampled and fused-speculative streams match the
  single-group path token for token, including across journal replay.
* **Never blocks the decode dispatch.** Landing only happens for
  transfer batches that are already ready on the wire (``is_ready``);
  backpressure past ``max_inflight_transfers`` blocks the prefill-side
  submitter instead.
* **Graceful fallback.** One-device hosts, ``prefill_fraction`` rounding
  to zero, or sliding-window models plan to ``None`` — the scheduler
  then runs plain time-overlap continuous fusion. A wedged transfer
  (watchdog: ``stall_timeout_s``, fault site ``disagg.transfer_stall``)
  degrades the request to in-group prefill and latches the router
  degraded, so admission never stalls behind a dead interconnect.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ...observability import get_registry
from ...utils.fault_injection import get_fault_injector
from ...utils.logging import logger
from .config_v2 import DisaggregationConfig, RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2
from .scheduling_utils import SchedulingError

# module-level handles (same idiom as engine_v2): both /metrics and the
# bench registry-delta percentiles read these
_obs = get_registry()
_transfer_bytes = _obs.histogram(
    "ds_disagg_transfer_bytes", "KV bytes per handoff transfer batch",
    lo=1.0, hi=1e12, buckets_per_decade=5)
_transfer_seconds = _obs.histogram(
    "ds_disagg_transfer_seconds",
    "Handoff transfer batch submit→land latency")
_handoffs_total = _obs.counter(
    "ds_disagg_handoffs_total", "Requests handed off prefill→decode")
_degraded_total = _obs.counter(
    "ds_disagg_degraded_total",
    "Requests degraded to in-group prefill (wedged or full handoff)")
_decode_stalls = _obs.counter(
    "ds_disagg_decode_stalls_total",
    "Tick-requests where decode waited on an unlanded handoff")
_queue_depth = _obs.gauge(
    "ds_disagg_queue_depth", "Handoff transfer batches in flight")
_prefill_occupancy = _obs.gauge(
    "ds_disagg_prefill_occupancy",
    "Live requests currently prefilling on the prefill group")
_decode_occupancy = _obs.gauge(
    "ds_disagg_decode_occupancy",
    "Live requests currently decoding on the decode group")


@dataclass
class GroupPlan:
    """The carve: which local devices prefill, which decode."""
    prefill_devices: Tuple
    decode_devices: Tuple
    prefill_tp: int = 1

    def describe(self) -> dict:
        return {
            "prefill_devices": [d.id for d in self.prefill_devices],
            "decode_devices": [d.id for d in self.decode_devices],
            "prefill_tp": self.prefill_tp,
        }


def plan_groups(cfg: DisaggregationConfig,
                devices=None) -> Optional[GroupPlan]:
    """Carve the local device set per config. Returns None when only one
    group fits (graceful fallback to continuous fusion) — unless explicit
    device lists were given, which raise if unhonorable."""
    if not cfg.enabled:
        return None
    devices = list(jax.local_devices()) if devices is None else list(devices)
    by_id = {d.id: d for d in devices}

    if cfg.prefill_devices is not None or cfg.decode_devices is not None:
        def _pick(ids, what):
            missing = [i for i in ids if i not in by_id]
            if missing:
                raise ValueError(
                    f"disaggregation.{what} names device ids {missing} "
                    f"not in the local set {sorted(by_id)}")
            return tuple(by_id[i] for i in ids)
        if cfg.prefill_devices is not None:
            prefill = _pick(cfg.prefill_devices, "prefill_devices")
            decode = (tuple(d for d in devices if d not in prefill)
                      if cfg.decode_devices is None
                      else _pick(cfg.decode_devices, "decode_devices"))
        else:
            decode = _pick(cfg.decode_devices, "decode_devices")
            prefill = tuple(d for d in devices if d not in decode)
        if not prefill or not decode:
            raise ValueError(
                f"disaggregation device lists leave an empty group "
                f"(prefill={len(prefill)}, decode={len(decode)}) on "
                f"{len(devices)} local devices")
    else:
        n = len(devices)
        k = int(round(cfg.prefill_fraction * n))
        k = min(k, n - 1)
        if n < 2 or k < 1:
            logger.info(
                f"disaggregation: prefill_fraction={cfg.prefill_fraction} "
                f"yields no prefill group on {n} device(s) — falling back "
                f"to time-overlap continuous fusion")
            return None
        # decode keeps the front of the list (including the process
        # default device, so the decode engine's default placement IS its
        # group); prefill takes the tail
        prefill, decode = tuple(devices[n - k:]), tuple(devices[:n - k])

    if len(prefill) % cfg.prefill_tp_size != 0:
        raise ValueError(
            f"disaggregation.prefill_tp_size={cfg.prefill_tp_size} does "
            f"not divide the {len(prefill)}-device prefill group")
    return GroupPlan(prefill, decode, cfg.prefill_tp_size)


@dataclass
class _Batch:
    """One in-flight transfer: a few blocks' worth of KV slices, already
    submitted to the wire via async device_put."""
    uid: int
    arrays: object          # ONE pytree, blocks concatenated on the slot dim
    dst_blocks: List[int]
    nbytes: int
    t_submit: float
    wedged: bool = False


@dataclass
class _Handoff:
    """Per-request handoff progress."""
    uid: int
    submitted: int = 0      # source blocks submitted to the wire so far
    dst_blocks: List[int] = field(default_factory=list)
    landed: int = 0         # blocks landed in the decode pool
    inflight: int = 0       # transfer batches not yet landed
    final: bool = False     # prompt fully fed; no more chunks coming
    seen_tokens: int = 0    # history length at final submit
    tokens: Optional[np.ndarray] = None  # that history (prefix registration)
    wedged: bool = False
    t_oldest: float = 0.0   # submit time of the oldest unlanded batch


class HandoffQueue:
    """Double-buffered, layer-batched async block mover between two
    engines' paged KV pools."""

    def __init__(self, src_engine: InferenceEngineV2,
                 dst_engine: InferenceEngineV2,
                 cfg: DisaggregationConfig):
        self._src = src_engine
        self._dst = dst_engine
        self._cfg = cfg
        self._bs = src_engine._state_manager.block_size
        assert self._bs == dst_engine._state_manager.block_size
        dst_model = dst_engine.model()
        self._dst_device = (dst_model.devices[0] if dst_model.devices
                            else jax.local_devices()[0])
        self._handoffs: Dict[int, _Handoff] = {}
        self._inflight: List[_Batch] = []
        # one compiled landing program per batch SIZE (not per block): a
        # donated in-place fori_loop of dynamic_update_slice along the slot
        # dim, pytree-shaped so the int8 (data, scales) cache lands both
        # leaves in one dispatch. Distinct sizes are bounded by
        # token_budget // block_size + 2, so the compile set stays tiny.
        self._land_fn = jax.jit(self._land_tree, donate_argnums=(0, ),
                                static_argnums=(3, ))

    @staticmethod
    def _land_tree(cache, upd, starts, bs):
        def body(i, c):
            return jax.tree_util.tree_map(
                lambda cc, uu: jax.lax.dynamic_update_slice_in_dim(
                    cc,
                    jax.lax.dynamic_slice_in_dim(uu, i * bs, bs, axis=1),
                    starts[i], axis=1),
                c, upd)
        return jax.lax.fori_loop(0, starts.shape[0], body, cache)

    # -- submission (prefill side) --------------------------------------

    def _block_nbytes(self) -> int:
        cache = self._src._state_manager.kv_cache.cache
        return sum(int(np.prod(a.shape[::2])) * self._bs * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(cache))

    def submit(self, uid: int, src_seq, final: bool,
               tokens: Optional[np.ndarray] = None) -> None:
        """Move every newly COMPLETED source block of ``uid`` onto the
        wire (final=True also ships the partial tail block and freezes the
        handoff). Raises SchedulingError when the decode pool cannot
        allocate the destination blocks — the caller degrades the request
        to in-group prefill."""
        h = self._handoffs.setdefault(uid, _Handoff(uid))
        if h.wedged:
            return
        seen = src_seq.seen_tokens
        n_done = ((seen + self._bs - 1) // self._bs if final
                  else seen // self._bs)
        src_blocks = src_seq.kv_blocks
        new = src_blocks[h.submitted:n_done]
        if final:
            h.final = True
            h.seen_tokens = int(seen)
            h.tokens = np.asarray(tokens, np.int32).reshape(-1)[:seen]
        if not new:
            return
        # reservation first: decode-pool blocks allocate at submit so the
        # scheduler's free_blocks/eviction arithmetic covers in-flight
        # handoffs exactly like live prefill
        dst = [int(b) for b in
               self._dst._state_manager.allocate_blocks(len(new))]
        h.dst_blocks.extend(dst)
        h.submitted = n_done

        src_cache = self._src._state_manager.kv_cache.cache
        # ONE gather per cache leaf pulls every new block's slots into a
        # contiguous [.., n*bs, ..] staging array, then ONE async
        # device_put for the whole chunk: the copy rides the wire while
        # the prefill engine runs the next chunk's forward
        idx = jnp.asarray(np.concatenate(
            [np.arange(b * self._bs, (b + 1) * self._bs) for b in new]),
            jnp.int32)
        gathered = jax.tree_util.tree_map(
            lambda a: jnp.take(a, idx, axis=1), src_cache)
        arrays = jax.device_put(gathered, self._dst_device)
        batch = _Batch(uid, arrays, dst, len(new) * self._block_nbytes(),
                       time.monotonic())
        if get_fault_injector().fire("disagg.transfer_stall",
                                     uid=uid) is not None:
            batch.wedged = True
            h.wedged = True
        h.inflight += 1
        if h.inflight == 1 or not h.t_oldest:
            h.t_oldest = batch.t_submit
        self._inflight.append(batch)
        _transfer_bytes.record(batch.nbytes)
        _queue_depth.set(len(self._inflight))
        # double-buffer backpressure: past the cap, the SUBMITTER waits
        # for the oldest healthy batch and lands it — prefill stalls,
        # decode never does
        while (len([b for b in self._inflight if not b.wedged])
               > max(1, self._cfg.max_inflight_transfers)):
            oldest = next(b for b in self._inflight if not b.wedged)
            for leaf in jax.tree_util.tree_leaves(oldest.arrays):
                leaf.block_until_ready()
            self._land(oldest)

    # -- landing (decode side) ------------------------------------------

    def _land(self, batch: _Batch) -> None:
        dst_kv = self._dst._state_manager.kv_cache
        starts = jnp.asarray([b * self._bs for b in batch.dst_blocks],
                             jnp.int32)
        dst_kv.cache = self._land_fn(dst_kv.cache, batch.arrays, starts,
                                     self._bs)
        self._inflight.remove(batch)
        h = self._handoffs.get(batch.uid)
        if h is not None:
            h.landed += len(batch.dst_blocks)
            h.inflight -= 1
            h.t_oldest = min((b.t_submit for b in self._inflight
                              if b.uid == batch.uid), default=0.0)
        _transfer_seconds.record(time.monotonic() - batch.t_submit)
        _queue_depth.set(len(self._inflight))

    def pump(self) -> List[int]:
        """Land every transfer batch that is ready on the wire; returns
        uids whose handoff is COMPLETE (final + fully landed) and ready
        for decode-side takeover. Never blocks: un-ready batches stay in
        flight, wedged ones are left to the watchdog."""
        for batch in list(self._inflight):
            if batch.wedged:
                continue
            if all(leaf.is_ready()
                   for leaf in jax.tree_util.tree_leaves(batch.arrays)):
                self._land(batch)
        return [uid for uid, h in self._handoffs.items()
                if h.final and not h.wedged and h.inflight == 0
                and h.landed == len(h.dst_blocks)]

    # -- lifecycle -------------------------------------------------------

    def get(self, uid: int) -> Optional[_Handoff]:
        return self._handoffs.get(uid)

    def active_uids(self):
        return list(self._handoffs)

    def stalled_uids(self, now: float, timeout_s: float) -> List[int]:
        """Wedged transfers plus anything older than the watchdog
        timeout."""
        out = []
        for uid, h in self._handoffs.items():
            if h.wedged:
                out.append(uid)
            elif h.inflight and h.t_oldest and now - h.t_oldest > timeout_s:
                out.append(uid)
        return out

    def finish(self, uid: int) -> _Handoff:
        """Takeover complete: forget the handoff (blocks now belong to the
        decode-side descriptor)."""
        h = self._handoffs.pop(uid)
        self._drop_batches(uid)
        return h

    def abort(self, uid: int) -> None:
        """Request left the handoff path (degrade, eviction, finish,
        quarantine): drop queued transfers and return the allocated
        decode-pool blocks."""
        h = self._handoffs.pop(uid, None)
        if h is None:
            return
        self._drop_batches(uid)
        if h.dst_blocks:
            self._dst._state_manager.release_blocks(h.dst_blocks)

    def _drop_batches(self, uid: int) -> None:
        self._inflight = [b for b in self._inflight if b.uid != uid]
        _queue_depth.set(len(self._inflight))

    @property
    def depth(self) -> int:
        return len(self._inflight)


class DisaggServing:
    """The scheduler-facing façade: prefill engine + group plan + handoff
    queue + degrade watchdog. Owned by ``ServingScheduler``; every method
    is called from the scheduler thread only."""

    def __init__(self, prefill_engine: InferenceEngineV2,
                 decode_engine: InferenceEngineV2,
                 plan: GroupPlan, cfg: DisaggregationConfig):
        self.prefill_engine = prefill_engine
        self.decode_engine = decode_engine
        self.plan = plan
        self.cfg = cfg
        self.queue = HandoffQueue(prefill_engine, decode_engine, cfg)
        self.degraded = False
        self._decode_stalled_uids = set()

    # -- routing ---------------------------------------------------------

    def route_to_prefill(self, feed_len: int) -> bool:
        """Should a prefilling request feed on the prefill group? No when
        degraded, or when the prefill pool cannot hold the remaining feed
        (in-group prefill is always a correct fallback)."""
        if self.degraded:
            return False
        bs = self.prefill_engine._state_manager.block_size
        need = (feed_len + bs - 1) // bs
        return need <= self.prefill_engine.free_blocks

    # -- per-tick driving ------------------------------------------------

    def advance(self, uid: int, final: bool,
                tokens: Optional[np.ndarray] = None) -> bool:
        """After a prefill chunk lands on the prefill engine: ship newly
        completed blocks. False = the decode pool refused the destination
        blocks — caller degrades the request to in-group prefill."""
        seq = self.prefill_engine._state_manager.get_sequence(uid)
        if seq is None:
            return True
        try:
            self.queue.submit(uid, seq, final, tokens)
        except SchedulingError:
            _degraded_total.inc()
            return False
        return True

    def pump(self, now: Optional[float] = None) -> Tuple[List[int], List[int]]:
        """Land ready transfers. Returns (takeover_ready_uids,
        degraded_uids); degraded uids have already been aborted here and
        latch the router degraded."""
        ready = self.queue.pump()
        now = time.monotonic() if now is None else now
        stalled = self.queue.stalled_uids(now, self.cfg.stall_timeout_s)
        for uid in stalled:
            logger.warning(
                f"disagg: handoff for uid={uid} wedged past "
                f"{self.cfg.stall_timeout_s}s — degrading to in-group "
                f"prefill; router latched degraded")
            self.abort(uid)
            _degraded_total.inc()
            self.degraded = True
        return [u for u in ready if u not in stalled], stalled

    def takeover(self, uid: int) -> None:
        """Handoff fully landed: the decode engine adopts the sequence
        (descriptor + prefix-cache registration over the landed blocks)
        and the prefill-side KV frees."""
        h = self.queue.finish(uid)
        self.decode_engine.adopt_handoff(uid, h.tokens, h.dst_blocks,
                                         h.seen_tokens)
        self.prefill_engine.flush(uid)
        _handoffs_total.inc()

    def abort(self, uid: int) -> None:
        self.queue.abort(uid)
        try:
            self.prefill_engine.flush(uid)
        except Exception:  # noqa: BLE001 — uid may be unknown to this side
            pass

    def in_handoff(self, uid: int) -> bool:
        h = self.queue.get(uid)
        return h is not None and h.final

    def note_decode_stall(self, uid: int) -> None:
        _decode_stalls.inc()
        self._decode_stalled_uids.add(uid)

    def refresh_occupancy(self, n_prefilling: int, n_decoding: int) -> None:
        _prefill_occupancy.set(n_prefilling)
        _decode_occupancy.set(n_decoding)

    # -- reporting -------------------------------------------------------

    def stats(self) -> dict:
        return {
            **self.plan.describe(),
            "degraded": self.degraded,
            "handoff_queue_depth": self.queue.depth,
            "handoffs_total": int(_handoffs_total.value),
            "degraded_total": int(_degraded_total.value),
            "decode_stalls_total": int(_decode_stalls.value),
            "prefill_free_blocks": self.prefill_engine.free_blocks,
            "decode_free_blocks": self.decode_engine.free_blocks,
        }


def build_disagg_llama(config=None, params=None,
                       engine_config: Optional[RaggedInferenceEngineConfig] = None,
                       seed: int = 0, **model_kwargs
                       ) -> Tuple[InferenceEngineV2, Optional[DisaggServing]]:
    """Build the serving engine(s) for the ``disaggregation`` config:
    returns ``(decode_engine, disagg)`` where ``disagg`` is None whenever
    the planner falls back to a single group — the decode engine is then
    byte-identical to a plain ``build_llama_engine`` build."""
    from ...models.llama import LlamaConfig, init_llama
    from .engine_v2 import build_llama_engine

    engine_config = engine_config or RaggedInferenceEngineConfig()
    cfg = engine_config.disaggregation
    plan = plan_groups(cfg)
    if plan is not None and params is None:
        # both engines must see the SAME weights; materialize once
        config = config or LlamaConfig.tiny()
        _, params = init_llama(config, seed=seed)
    if plan is not None and getattr(config, "sliding_window", None):
        logger.warning(
            "disaggregation disabled: sliding-window models release "
            "trailing KV blocks mid-sequence, which the block-granular "
            "handoff does not carry")
        plan = None
    decode_engine = build_llama_engine(
        config, params=params, engine_config=engine_config, seed=seed,
        devices=list(plan.decode_devices) if plan is not None else None,
        **model_kwargs)
    if plan is None:
        return decode_engine, None

    p_cfg = engine_config.model_copy(deep=True)
    p_cfg.tensor_parallel.tp_size = cfg.prefill_tp_size
    if cfg.prefill_kv_blocks is not None:
        p_cfg.num_kv_blocks = cfg.prefill_kv_blocks
    prefill_engine = build_llama_engine(
        decode_engine.model().config, params=params, engine_config=p_cfg,
        seed=seed, devices=list(plan.prefill_devices), **model_kwargs)
    logger.info(
        f"disaggregated serving: prefill group "
        f"{[d.id for d in plan.prefill_devices]} (tp={plan.prefill_tp}), "
        f"decode group {[d.id for d in plan.decode_devices]}")
    return decode_engine, DisaggServing(prefill_engine, decode_engine,
                                        plan, cfg)
